"""ISSUE 10 — overload-safe serving: end-to-end deadlines, retry
budgets, and admission control.

Covers the tentpole and its satellites:

- the contextvar budget (`service/deadline.py`): scope tightening,
  expiry, the fail-fast guard, wire form;
- the wire field on every codec — npwire flag 16, npproto field 18,
  shm doorbell flag 4 — with the BYTE-IDENTICAL contract for
  deadline-free frames and the reference-protobuf-runtime-ignores-it
  contract for field 18;
- server enforcement: admission rejection of expired work (in-band
  npwire error / npproto DEADLINE_EXCEEDED abort), micro-batcher queue
  shedding, bounded-queue admission control (`max_queue` /
  `max_inflight_bytes` + retryable UNAVAILABLE);
- client classification: in-band deadline errors raise
  `DeadlineExceeded`; gRPC `DEADLINE_EXCEEDED` is NON-retryable on
  both codecs (the PR-1 status-table satellite); bounded reads against
  a server that accepts then never replies (TCP + shm) surface as the
  TRANSIENT classification inside the budget;
- the per-pool retry budget (`routing/budget.py`): token-bucket
  semantics, hedges/failover/fanout member re-runs charging it, refill
  reconvergence;
- the `slow_compute` fault kind: seeded, bounded, replayable.
"""

import asyncio
import socket
import struct
import threading
import time

import grpc
import numpy as np
import pytest

from pytensor_federated_tpu.service import deadline as dl
from pytensor_federated_tpu.service import npproto_codec as npp
from pytensor_federated_tpu.service import npwire
from pytensor_federated_tpu.service.npwire import WireError


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _double(x):
    return [2.0 * np.asarray(x)]


# ---------------------------------------------------------------------------
# the budget itself
# ---------------------------------------------------------------------------


class TestDeadlineModule:
    def test_unbounded_default(self):
        assert dl.current_deadline() is None
        assert dl.remaining_s() is None
        assert not dl.expired()
        assert dl.wire_budget() is None
        assert dl.check_remaining("here") is None  # no-op, no raise

    def test_scope_binds_and_restores(self):
        with dl.deadline_scope(5.0):
            r = dl.remaining_s()
            assert r is not None and 4.0 < r <= 5.0
            assert dl.wire_budget() is not None
        assert dl.remaining_s() is None

    def test_nested_scopes_only_tighten(self):
        with dl.deadline_scope(0.5):
            outer = dl.current_deadline()
            with dl.deadline_scope(60.0):
                # An inner retry loop cannot mint itself fresh budget.
                assert dl.current_deadline() == outer
            with dl.deadline_scope(0.01):
                assert dl.current_deadline() < outer

    def test_none_scope_is_a_no_op(self):
        with dl.deadline_scope(None):
            assert dl.remaining_s() is None

    def test_expiry_and_fail_fast(self):
        with dl.deadline_scope(0.0):
            time.sleep(0.002)
            assert dl.expired()
            with pytest.raises(dl.DeadlineExceeded) as ei:
                dl.check_remaining("encode")
            assert dl.is_deadline_error(str(ei.value))

    def test_classification_is_substring_not_prefix(self):
        # Servers wrap shed messages in their own stage prefixes.
        assert dl.is_deadline_error(
            "compute error: deadline exceeded: shed in queue"
        )
        assert not dl.is_deadline_error("sigma must be positive")
        assert not dl.is_deadline_error(None)

    def test_deadline_exceeded_is_deterministic_for_pools(self):
        """RuntimeError subclass on purpose: every lane classifies it
        as non-transient, so failover/retry never amplify a spent
        budget."""
        from pytensor_federated_tpu.routing import NodePool

        exc = dl.DeadlineExceeded(dl.deadline_error("x"))
        assert isinstance(exc, RuntimeError)
        assert not NodePool().is_transient(exc)

    def test_crosses_executor_with_copy_context(self):
        import contextvars
        from concurrent.futures import ThreadPoolExecutor

        with dl.deadline_scope(5.0):
            ctx = contextvars.copy_context()
            with ThreadPoolExecutor(1) as ex:
                r = ex.submit(ctx.run, dl.remaining_s).result()
        assert r is not None and r > 4.0


# ---------------------------------------------------------------------------
# the wire field, all three codecs
# ---------------------------------------------------------------------------


class TestNpwireDeadlineField:
    def test_deadline_free_frame_is_byte_identical(self):
        """The acceptance invariant: no deadline bound -> the exact
        pre-deadline frame (flag clear, no block)."""
        a = [np.arange(6, dtype=np.float32)]
        frame = npwire.encode_arrays(a, uuid=b"u" * 16)
        assert not frame[npwire._FLAGS_OFF] & npwire._FLAG_DEADLINE
        # Hand-assembled pre-ISSUE-10 layout for this exact frame.
        payload = a[0].tobytes()
        expected = (
            struct.pack("<4sBB16sI", b"NPW1", 1, 0, b"u" * 16, 1)
            + struct.pack("<H", 3) + b"<f4"
            + struct.pack("<B", 1) + struct.pack("<Q", 6)
            + struct.pack("<Q", len(payload)) + payload
        )
        assert frame == expected
        assert npwire.peek_deadline(frame) is None

    def test_roundtrip_with_deadline(self):
        a = [np.arange(4.0)]
        frame = npwire.encode_arrays(
            a, uuid=b"u" * 16, trace_id=b"t" * 16, deadline_s=1.25
        )
        assert npwire.peek_deadline(frame) == 1.25
        arrays, uuid, error, trace_id, _sp = npwire.decode_arrays_all(
            frame
        )
        np.testing.assert_array_equal(arrays[0], a[0])
        assert (uuid, error, trace_id) == (b"u" * 16, None, b"t" * 16)

    def test_batch_frame_carries_outer_deadline(self):
        item = npwire.encode_arrays([np.ones(2)], uuid=b"i" * 16)
        frame = npwire.encode_batch(
            [item], uuid=b"w" * 16, deadline_s=0.5
        )
        assert npwire.peek_deadline(frame) == 0.5
        items, uuid, _e, _t, _s = npwire.decode_batch(frame)
        assert items == [item] and uuid == b"w" * 16

    def test_truncated_deadline_block_is_loud(self):
        frame = npwire.encode_arrays(
            [np.ones(1)], uuid=b"u" * 16, deadline_s=1.0
        )
        off = struct.calcsize("<4sBB16sI")
        with pytest.raises(WireError):
            npwire.decode_arrays_all(frame[: off + 4])
        with pytest.raises(WireError):
            npwire.peek_deadline(frame[: off + 4])

    def test_sg_encoder_matches_contiguous(self):
        a = [np.arange(8, dtype=np.float64)]
        vec = npwire.encode_arrays_sg(
            a, uuid=b"u" * 16, deadline_s=2.0
        )
        assert b"".join(
            bytes(p) for p in vec
        ) == npwire.encode_arrays(a, uuid=b"u" * 16, deadline_s=2.0)

    def test_frame_uuid_fixed_offset(self):
        frame = npwire.encode_arrays(
            [], uuid=b"q" * 16, deadline_s=-1.0
        )
        assert npwire.frame_uuid(frame) == b"q" * 16
        with pytest.raises(WireError):
            npwire.frame_uuid(b"NPW1\x01")


class TestNpprotoDeadlineField:
    def test_deadline_free_message_unchanged(self):
        a = [np.arange(3.0)]
        msg = npp.encode_arrays_msg(a, uuid="abc")
        assert npp.peek_deadline_msg(msg) is None
        # Field 18 never appears without a deadline.
        assert npp._tag(18, npp._WT_I64) not in msg

    def test_roundtrip_and_peek(self):
        a = [np.arange(3.0)]
        msg = npp.encode_arrays_msg(a, uuid="abc", deadline_s=3.5)
        assert npp.peek_deadline_msg(msg) == 3.5
        arrays, uuid, error, _t, _s = npp.decode_arrays_msg_full(msg)
        np.testing.assert_array_equal(arrays[0], a[0])
        assert (uuid, error) == ("abc", None)

    def test_batch_message_carries_outer_deadline(self):
        item = npp.encode_arrays_msg([np.ones(2)], uuid="i")
        msg = npp.encode_batch_msg([item], uuid="w", deadline_s=0.25)
        assert npp.peek_deadline_msg(msg) == 0.25
        items, uuid, _t, _s = npp.decode_batch_msg(msg)
        assert items == [item] and uuid == "w"

    def test_reference_protobuf_runtime_skips_field_18(self):
        """The forward-compatibility acceptance: an unmodified
        reference peer (official protobuf runtime) parses a message
        carrying field 18 and sees the same items/uuid."""
        protobuf = pytest.importorskip("google.protobuf")
        from google.protobuf import descriptor_pb2, descriptor_pool
        from google.protobuf import message_factory

        del protobuf
        fdp = descriptor_pb2.FileDescriptorProto()
        fdp.name = "ref_deadline.proto"
        fdp.syntax = "proto3"
        msg_t = fdp.message_type.add()
        msg_t.name = "InputArrays"
        item_f = msg_t.field.add()
        item_f.name = "items"
        item_f.number = 1
        item_f.type = descriptor_pb2.FieldDescriptorProto.TYPE_BYTES
        item_f.label = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
        uuid_f = msg_t.field.add()
        uuid_f.name = "uuid"
        uuid_f.number = 2
        uuid_f.type = descriptor_pb2.FieldDescriptorProto.TYPE_STRING
        uuid_f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
        pool = descriptor_pool.DescriptorPool()
        pool.Add(fdp)
        desc = pool.FindMessageTypeByName("InputArrays")
        cls = message_factory.GetMessageClass(desc)
        wire = npp.encode_arrays_msg(
            [np.ones(2)], uuid="ref-check", deadline_s=9.75
        )
        parsed = cls.FromString(wire)
        assert parsed.uuid == "ref-check"
        assert len(parsed.items) == 1  # field 18 skipped by wire type


class TestShmDeadlineField:
    def test_frame_flag_and_roundtrip(self):
        from pytensor_federated_tpu.service import shm

        bare = shm.encode_frame(shm._KIND_EVAL, b"u" * 16, b"body")
        assert not bare[6] & shm._FLAG_DEADLINE  # flags byte offset 6
        k, u, e, t, d, _part, _ver, off, frame = shm.decode_frame(bare)
        assert d is None and frame[off:] == b"body"
        stamped = shm.encode_frame(
            shm._KIND_EVAL, b"u" * 16, b"body", deadline_s=0.75
        )
        k, u, e, t, d, _part, _ver, off, frame = shm.decode_frame(stamped)
        assert d == 0.75 and frame[off:] == b"body"
        # The deadline block is exactly the 8-byte delta.
        assert len(stamped) == len(bare) + 8

    def test_truncated_deadline_block_is_loud(self):
        from pytensor_federated_tpu.service import shm

        stamped = shm.encode_frame(
            shm._KIND_EVAL, b"u" * 16, deadline_s=0.75
        )
        with pytest.raises(WireError):
            shm.decode_frame(stamped[:-4])


# ---------------------------------------------------------------------------
# server enforcement
# ---------------------------------------------------------------------------


class TestServeNpwirePayloadAdmission:
    """The TCP/shm shared serving path (`tcp.serve_npwire_payload`)."""

    def test_expired_plain_frame_rejected_in_band(self):
        from pytensor_federated_tpu.service.tcp import serve_npwire_payload

        req = npwire.encode_arrays(
            [np.ones(2)], uuid=b"q" * 16, deadline_s=-0.5
        )
        reply = serve_npwire_payload(_double, req)
        arrays, uuid, error = npwire.decode_arrays(reply)
        assert uuid == b"q" * 16 and arrays == []
        assert dl.is_deadline_error(error)

    def test_expired_batch_frame_rejected_in_band(self):
        from pytensor_federated_tpu.service.tcp import serve_npwire_payload

        item = npwire.encode_arrays([np.ones(2)], uuid=b"i" * 16)
        req = npwire.encode_batch(
            [item], uuid=b"w" * 16, deadline_s=-0.5
        )
        reply = serve_npwire_payload(_double, req)
        items, uuid, error, _t, _s = npwire.decode_batch(reply)
        assert uuid == b"w" * 16 and items == []
        assert dl.is_deadline_error(error)

    def test_live_budget_is_served_and_bound(self):
        from pytensor_federated_tpu.service.tcp import serve_npwire_payload

        seen = {}

        def compute(x):
            seen["remaining"] = dl.remaining_s()
            return [2.0 * np.asarray(x)]

        req = npwire.encode_arrays(
            [np.arange(3.0)], uuid=b"q" * 16, deadline_s=5.0
        )
        reply = serve_npwire_payload(compute, req)
        arrays, _u, error = npwire.decode_arrays(reply)
        assert error is None
        np.testing.assert_array_equal(arrays[0], 2.0 * np.arange(3.0))
        # The compute ran under the adopted budget.
        assert seen["remaining"] is not None and 0 < seen["remaining"] <= 5.0


class TestTcpBatchedWindowDeadline:
    def test_outer_batch_frame_carries_budget_to_server(self):
        """Regression (round-10 review): the TCP batched-window path
        must stamp the deadline on the OUTER batch frame — the server
        peeks only that frame, so an unstamped outer frame silently
        skipped admission and compute never ran under the budget
        (the gRPC `_encode_batch_frame` and shm doorbell lanes always
        stamped theirs)."""
        from pytensor_federated_tpu.service import serve_tcp_once
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        seen = []

        def compute(x):
            seen.append(dl.remaining_s())
            return [2.0 * np.asarray(x)]

        ready = {}
        ev = threading.Event()

        def cb(p):
            ready["port"] = p
            ev.set()

        threading.Thread(
            target=serve_tcp_once,
            args=(compute,),
            kwargs=dict(ready_callback=cb, max_connections=1),
            daemon=True,
        ).start()
        assert ev.wait(10)
        client = TcpArraysClient("127.0.0.1", ready["port"])
        try:
            reqs = [(np.array([float(i)]),) for i in range(4)]
            with dl.deadline_scope(5.0):
                res = client.evaluate_many(reqs, window=4, batch=True)
            for i, out in enumerate(res):
                np.testing.assert_array_equal(out[0], [2.0 * i])
            assert len(seen) == 4
            # Every item computed under the adopted wire budget.
            assert all(r is not None and 0 < r <= 5.0 for r in seen)
        finally:
            client.close()


class TestGrpcServiceAdmission:
    def test_expired_npwire_request_is_in_band_deadline_error(self):
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
        )

        service = ArraysToArraysService(_double)
        req = npwire.encode_arrays(
            [np.ones(2)], uuid=b"q" * 16, deadline_s=-1.0
        )
        reply = asyncio.run(service.evaluate(req, None))
        arrays, uuid, error = npwire.decode_arrays(reply)
        assert uuid == b"q" * 16 and dl.is_deadline_error(error)

    def test_expired_npproto_request_raises_deadline_exceeded(self):
        """No in-band error field on the reference wire: the handler
        raises and the RPC layer aborts as DEADLINE_EXCEEDED."""
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
        )

        service = ArraysToArraysService(_double)
        req = npp.encode_arrays_msg(
            [np.ones(2)], uuid="q", deadline_s=-1.0
        )
        with pytest.raises(dl.DeadlineExceeded):
            asyncio.run(service.evaluate(req, None))


class TestMicroBatcherShed:
    def test_expired_entry_shed_never_computed(self):
        from pytensor_federated_tpu.service.batching import MicroBatcher

        computed = []

        def compute(x):
            computed.append(float(np.asarray(x)[0]))
            return [np.asarray(x)]

        async def main():
            b = MicroBatcher(compute, inline=True)
            with dl.deadline_scope(0.0):
                expired = asyncio.ensure_future(
                    b.submit([np.array([1.0])])
                )
            live = asyncio.ensure_future(b.submit([np.array([2.0])]))
            with pytest.raises(dl.DeadlineExceeded):
                await expired
            out = await live
            np.testing.assert_array_equal(out[0], [2.0])

        asyncio.run(main())
        # The expired entry was shed BEFORE compute, never vmap'd in.
        assert computed == [2.0]

    def test_shed_expired_clears_queue_and_counts(self):
        from pytensor_federated_tpu.service.batching import MicroBatcher

        async def main():
            b = MicroBatcher(_double, inline=True)
            with dl.deadline_scope(0.0):
                dead = [
                    b._enqueue([np.array([float(i)])], start=False)
                    for i in range(3)
                ]
            live = b._enqueue([np.array([9.0])], start=False)
            assert b.queue_depth == 4
            assert b.shed_expired() == 3
            assert b.queue_depth == 1
            assert b.stats()["shed_total"] == 3
            for fut in dead:
                with pytest.raises(dl.DeadlineExceeded):
                    await fut
            b._start()
            out = await live
            np.testing.assert_array_equal(out[0], [18.0])

        asyncio.run(main())


class TestAdmissionControl:
    def _service(self, **kw):
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
        )

        release = threading.Event()

        def compute(x):
            release.wait(5.0)
            return [2.0 * np.asarray(x)]

        return ArraysToArraysService(compute, max_batch=1, **kw), release

    def test_full_queue_refused_retryably(self):
        service, release = self._service(max_queue=1)
        req = npwire.encode_arrays([np.ones(1)], uuid=b"a" * 16)

        async def main():
            inflight = asyncio.ensure_future(service.evaluate(req, None))
            await asyncio.sleep(0.05)  # genuinely in flight
            # context=None direct-call path raises ConnectionError;
            # over real gRPC this is an UNAVAILABLE abort — the
            # RETRYABLE classification, like the drain rejection.
            with pytest.raises(ConnectionError, match="overloaded"):
                await service.evaluate(req, None)
            release.set()
            reply = await inflight
            _arrays, _u, error = npwire.decode_arrays(reply)
            assert error is None

        asyncio.run(main())

    def test_inflight_bytes_cap_with_idle_exemption(self):
        service, release = self._service(max_inflight_bytes=64)
        big = npwire.encode_arrays(
            [np.zeros(64, np.float64)], uuid=b"b" * 16
        )

        async def main():
            # Idle exemption: one oversized request is served, not
            # refused forever.
            first = asyncio.ensure_future(service.evaluate(big, None))
            await asyncio.sleep(0.05)
            with pytest.raises(ConnectionError, match="overloaded"):
                await service.evaluate(big, None)
            release.set()
            await first

        asyncio.run(main())

    def test_unbounded_by_default(self):
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
        )

        service = ArraysToArraysService(_double)
        assert service.max_queue is None
        assert service.max_inflight_bytes is None

    def test_shed_makes_room_for_unary_traffic(self):
        """Regression (round-10 review): a shed entry's handler keeps
        _inflight_rpcs inflated until a later loop tick, so the
        admission recheck must count the room the shed freed — else
        shedding can never admit a live unary request and the
        shed-then-recheck is dead code on that lane."""
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
        )

        release = threading.Event()

        def compute(x):
            release.wait(10.0)
            return [2.0 * np.asarray(x)]

        def batch(reqs):
            return [compute(*r) for r in reqs]

        service = ArraysToArraysService(
            compute, batch_fn=batch, max_batch=4, max_queue=3
        )
        live = npwire.encode_arrays([np.ones(1)], uuid=b"l" * 16)

        async def main():
            first = asyncio.ensure_future(service.evaluate(live, None))
            await asyncio.sleep(0.05)  # occupies the compute thread
            doomed = [
                asyncio.ensure_future(
                    service.evaluate(
                        npwire.encode_arrays(
                            [np.ones(1)],
                            uuid=bytes([65 + i]) * 16,
                            deadline_s=0.05,
                        ),
                        None,
                    )
                )
                for i in range(2)
            ]
            await asyncio.sleep(0.2)  # both parked + expired in queue
            assert service._inflight_rpcs == 3
            assert service._batcher.queue_depth == 2
            # depth == max_queue: the live request triggers the shed
            # and must be ADMITTED on the spot.
            fourth = asyncio.ensure_future(service.evaluate(live, None))
            await asyncio.sleep(0.05)
            release.set()
            reply = await fourth
            _a, _u, error = npwire.decode_arrays(reply)
            assert error is None
            for fut in doomed:
                _a, _u, err = npwire.decode_arrays(await fut)
                assert dl.is_deadline_error(err)
            await first

        asyncio.run(main())


# ---------------------------------------------------------------------------
# client classification (PR-1 satellite: the gRPC status table)
# ---------------------------------------------------------------------------


class _FakeRpcError(grpc.aio.AioRpcError):
    def __init__(self, code):
        self._fake_code = code

    def code(self):
        return self._fake_code


class TestStatusClassification:
    def test_deadline_exceeded_is_non_retryable(self):
        from pytensor_federated_tpu.service.client import (
            _NO_RETRY_STATUS,
            _is_retryable,
        )

        assert grpc.StatusCode.DEADLINE_EXCEEDED in _NO_RETRY_STATUS
        assert not _is_retryable(
            _FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED)
        )
        assert _is_retryable(_FakeRpcError(grpc.StatusCode.UNAVAILABLE))

    def test_pool_classification_matches(self):
        from pytensor_federated_tpu.routing import NodePool
        from pytensor_federated_tpu.routing.pooled_client import (
            _is_transport_error,
        )

        exc = _FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED)
        assert not NodePool().is_transient(exc)
        assert not _is_transport_error(exc)
        ok = _FakeRpcError(grpc.StatusCode.UNAVAILABLE)
        assert NodePool().is_transient(ok)
        assert _is_transport_error(ok)


class TestGrpcClientDeadlineE2E:
    @pytest.mark.parametrize("codec", ["npwire", "npproto"])
    def test_expired_budget_fails_fast_both_codecs(self, codec):
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )

        # No server needed: the fail-fast guard fires before connect.
        client = ArraysToArraysServiceClient(
            "127.0.0.1", 1, codec=codec, use_stream=False
        )

        async def main():
            with dl.deadline_scope(0.0):
                await asyncio.sleep(0.002)
                with pytest.raises(dl.DeadlineExceeded):
                    await client.evaluate_async(np.ones(2))

        asyncio.run(main())

    @pytest.mark.parametrize("codec", ["npwire", "npproto"])
    @pytest.mark.parametrize("use_stream", [False, True])
    def test_roundtrip_under_deadline_both_codecs(self, codec, use_stream):
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )
        from pytensor_federated_tpu.service.server import serve

        port = _free_port()

        async def main():
            server = await serve(_double, port=port)
            try:
                client = ArraysToArraysServiceClient(
                    "127.0.0.1", port, codec=codec,
                    use_stream=use_stream,
                )
                with dl.deadline_scope(10.0):
                    out = await client.evaluate_async(np.arange(3.0))
                np.testing.assert_array_equal(
                    out[0], 2.0 * np.arange(3.0)
                )
            finally:
                await server.stop(None)

        asyncio.run(main())

    @pytest.mark.parametrize("codec", ["npwire", "npproto"])
    def test_slow_server_sheds_inside_budget_both_codecs(self, codec):
        """A compute slower than the budget: the npwire lane sheds via
        the deadline classification; the npproto lane surfaces the
        non-retryable DEADLINE_EXCEEDED status — both inside ~the
        budget, never the watchdog."""
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )
        from pytensor_federated_tpu.service.server import serve

        def slow(x):
            time.sleep(1.0)
            return [np.asarray(x)]

        port = _free_port()

        async def main():
            server = await serve(slow, port=port)
            try:
                client = ArraysToArraysServiceClient(
                    "127.0.0.1", port, codec=codec, use_stream=False
                )
                t0 = time.monotonic()
                with dl.deadline_scope(0.2):
                    with pytest.raises(
                        (dl.DeadlineExceeded, grpc.aio.AioRpcError)
                    ) as ei:
                        await client.evaluate_async(np.ones(2))
                assert time.monotonic() - t0 < 1.0
                if isinstance(ei.value, grpc.aio.AioRpcError):
                    assert (
                        ei.value.code()
                        == grpc.StatusCode.DEADLINE_EXCEEDED
                    )
            finally:
                await server.stop(None)

        asyncio.run(main())


# ---------------------------------------------------------------------------
# shared admission telemetry + retry restamping (round-10 review)
# ---------------------------------------------------------------------------


class TestAdmissionShedTelemetryUnified:
    def test_tcp_expired_admission_bumps_shared_counter(self):
        """Regression (round-10 review): the tcp/shm admission paths
        recorded the flightrec shed but never bumped
        ``pftpu_admission_shed_total`` — only the grpc lane did.  All
        three now go through ``deadline.shed_expired_admission``."""
        from pytensor_federated_tpu.service.tcp import serve_npwire_payload
        from pytensor_federated_tpu.telemetry import spans as tspans

        prev = tspans.set_enabled(True)
        try:
            before = dl.ADMISSION_SHED.labels(reason="expired").value
            req = npwire.encode_arrays(
                [np.ones(2)], uuid=b"q" * 16, deadline_s=-0.5
            )
            reply = serve_npwire_payload(_double, req)
            _arrays, _uuid, error = npwire.decode_arrays(reply)
            assert dl.is_deadline_error(error)
            assert (
                dl.ADMISSION_SHED.labels(reason="expired").value
                == before + 1
            )
        finally:
            tspans.set_enabled(prev)


def _recv_exact_raw(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class TestRetryRestampsBudget:
    """Regression (round-10 review): the tcp and grpc clients encoded
    the deadline once and re-sent the identical frame on every retry,
    so a retried request advertised the budget as it stood BEFORE the
    failed attempts burned wall time — the server would admit (and the
    batcher keep) work whose caller was closer to giving up than the
    wire claimed.  The retry loops now restamp the remaining budget
    (the shm lane always recomputed it per attempt)."""

    def test_tcp_retry_frame_carries_fresh_budget(self):
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        frames = []
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        port = srv.getsockname()[1]

        def run():
            # Attempt 0: read the frame, burn 0.3 s of the caller's
            # budget, close without replying -> the client retries.
            conn, _ = srv.accept()
            (n,) = struct.unpack("<I", _recv_exact_raw(conn, 4))
            frames.append(_recv_exact_raw(conn, n))
            time.sleep(0.3)
            conn.close()
            # Attempt 1: read the frame, answer properly.
            conn, _ = srv.accept()
            (n,) = struct.unpack("<I", _recv_exact_raw(conn, 4))
            frames.append(_recv_exact_raw(conn, n))
            reply = npwire.encode_arrays(
                [np.zeros(1)], uuid=npwire.frame_uuid(frames[-1])
            )
            conn.sendall(struct.pack("<I", len(reply)) + reply)
            conn.close()

        threading.Thread(target=run, daemon=True).start()
        client = TcpArraysClient("127.0.0.1", port, retries=2)
        try:
            with dl.deadline_scope(10.0):
                client.evaluate(np.ones(2))
        finally:
            client.close()
            srv.close()
        assert len(frames) == 2
        b0 = npwire.peek_deadline(frames[0])
        b1 = npwire.peek_deadline(frames[1])
        assert b0 is not None and b1 is not None
        assert b1 <= b0 - 0.25  # the burned wall time is on the wire

    def test_grpc_retry_request_carries_fresh_budget(self):
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )

        # No server needed: intercept the encoded request per attempt.
        client = ArraysToArraysServiceClient(
            "127.0.0.1", 1, codec="npwire", use_stream=False
        )
        captured = []

        async def fake_evaluate_once(request):
            captured.append(bytes(request))
            await asyncio.sleep(0.25)  # burn budget between attempts
            raise ConnectionError("synthetic transport failure")

        client._evaluate_once = fake_evaluate_once

        async def main():
            with dl.deadline_scope(10.0):
                with pytest.raises((ConnectionError, RuntimeError)):
                    await client.evaluate_async(np.ones(2))

        asyncio.run(main())
        assert len(captured) >= 2
        b0 = npwire.peek_deadline(captured[0])
        b1 = npwire.peek_deadline(captured[1])
        assert b0 is not None and b1 is not None
        assert b1 <= b0 - 0.2


# ---------------------------------------------------------------------------
# bounded reads against a stalling server (TCP + shm satellite)
# ---------------------------------------------------------------------------


def _stalling_server():
    """Accepts, reads the request, never replies — the silent-peer
    hole the bounded reads exist for.  Returns (port, server_socket)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)

    def run():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(
                target=lambda c: (c.recv(1 << 16), time.sleep(60)),
                args=(conn,),
                daemon=True,
            ).start()

    threading.Thread(target=run, daemon=True).start()
    return srv.getsockname()[1], srv


def _dripping_server(drip_s=0.15, total=64):
    """Accepts, reads the request, then replies a long frame ONE BYTE
    at a time with gaps just under any per-recv timeout — the
    slow-drip evasion the TOTAL bound exists for.  Returns
    (port, server_socket)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)

    def serve(conn):
        try:
            conn.recv(1 << 16)
            conn.sendall(struct.pack("<I", total))
            for _ in range(total):
                conn.sendall(b"x")
                time.sleep(drip_s)
        except OSError:
            pass

    def run():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(
                target=serve, args=(conn,), daemon=True
            ).start()

    threading.Thread(target=run, daemon=True).start()
    return srv.getsockname()[1], srv


class TestTcpBoundedRecv:
    def test_dripping_server_cannot_evade_total_budget(self):
        """Regression (round-10 review): `settimeout` bounds ONE recv,
        so a peer dripping bytes just under it stretched a multi-recv
        frame read ~drip_interval*bytes past the budget; the shared
        bounded_reader re-arms the REMAINING budget before each chunk,
        keeping the TOTAL read inside it."""
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        port, srv = _dripping_server(drip_s=0.15, total=64)
        try:
            client = TcpArraysClient("127.0.0.1", port, retries=0)
            t0 = time.monotonic()
            with dl.deadline_scope(0.5):
                with pytest.raises((ConnectionError, OSError)):
                    client.evaluate(np.ones(2))
            wall = time.monotonic() - t0
            # Old per-recv semantics would block ~64*0.15 = 9.6 s.
            assert wall < 2.0, f"drip evaded the budget: {wall:.2f}s"
        finally:
            srv.close()

    def test_stalling_server_classified_transient_inside_budget(self):
        from pytensor_federated_tpu.routing import NodePool
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        port, srv = _stalling_server()
        try:
            client = TcpArraysClient("127.0.0.1", port, retries=0)
            t0 = time.monotonic()
            with dl.deadline_scope(0.3):
                with pytest.raises((ConnectionError, OSError)) as ei:
                    client.evaluate(np.ones(2))
            assert time.monotonic() - t0 < 2.0
            # The transient classification: pools fail this over.
            assert NodePool().is_transient(ei.value)
        finally:
            srv.close()

    def test_explicit_timeout_s_without_ambient_deadline(self):
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        port, srv = _stalling_server()
        try:
            client = TcpArraysClient(
                "127.0.0.1", port, retries=0, timeout_s=0.2
            )
            t0 = time.monotonic()
            with pytest.raises((ConnectionError, OSError)):
                client.evaluate(np.ones(2))
            assert time.monotonic() - t0 < 2.0
        finally:
            srv.close()

    def test_no_timeout_no_deadline_keeps_blocking_semantics(self):
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        client = TcpArraysClient("127.0.0.1", 1)
        assert dl.recv_budget_s(client.timeout_s) is None
        with dl.deadline_scope(1.0):
            t = dl.recv_budget_s(client.timeout_s)
            assert t is not None and 0 < t <= 1.0

    def test_deadline_spent_midwindow_raises_deadline_class(self):
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        port, srv = _stalling_server()
        try:
            client = TcpArraysClient("127.0.0.1", port, retries=2)
            # Retries are stopped by the spent budget (check_remaining
            # in the retry loop), so the whole call stays inside ~one
            # budget instead of 3x.
            t0 = time.monotonic()
            with dl.deadline_scope(0.3):
                with pytest.raises(
                    (dl.DeadlineExceeded, ConnectionError, OSError)
                ):
                    client.evaluate(np.ones(2))
            assert time.monotonic() - t0 < 1.5
        finally:
            srv.close()


class TestShmBoundedRecv:
    def test_stalling_doorbell_classified_inside_budget(self):
        from pytensor_federated_tpu.service.shm import ShmArraysClient

        port, srv = _stalling_server()
        try:
            client = ShmArraysClient("127.0.0.1", port, retries=0)
            t0 = time.monotonic()
            with dl.deadline_scope(0.3):
                with pytest.raises((ConnectionError, OSError)):
                    client.evaluate(np.ones(2))
            assert time.monotonic() - t0 < 2.0
        finally:
            srv.close()


class TestShmDeadlineE2E:
    def test_expired_wire_budget_rejected_at_shm_admission(
        self, monkeypatch
    ):
        """Server-side enforcement on the doorbell: a frame whose
        stamped budget is spent is answered in band, never computed.
        The client-side fail-fast is disarmed so the SERVER is the
        judge (the real race this guards: budget dies in flight)."""
        from pytensor_federated_tpu.service.shm import (
            ShmArraysClient,
            serve_shm,
        )

        computed = []

        def compute(x):
            computed.append(1)
            return [2.0 * np.asarray(x)]

        ports = []
        threading.Thread(
            target=serve_shm,
            args=(compute,),
            kwargs=dict(ready_callback=ports.append, max_connections=1),
            daemon=True,
        ).start()
        deadline_t = time.time() + 10.0
        while not ports and time.time() < deadline_t:
            time.sleep(0.005)
        assert ports, "shm node did not come up"
        client = ShmArraysClient(
            "127.0.0.1", ports[0], connect_timeout_s=5.0
        )
        try:
            out = client.evaluate(np.arange(3.0))
            np.testing.assert_array_equal(out[0], 2.0 * np.arange(3.0))
            monkeypatch.setattr(dl, "wire_budget", lambda: -1.0)
            monkeypatch.setattr(dl, "check_remaining", lambda where: None)
            monkeypatch.setattr(dl, "remaining_s", lambda: None)
            from pytensor_federated_tpu.telemetry import spans as tspans

            prev = tspans.set_enabled(True)
            try:
                before = dl.ADMISSION_SHED.labels(reason="expired").value
                with pytest.raises(dl.DeadlineExceeded):
                    client.evaluate(np.arange(3.0))
                # Regression (round-10 review): the shm admission path
                # recorded the flightrec shed but never bumped the
                # shared counter — only the grpc lane did.
                assert (
                    dl.ADMISSION_SHED.labels(reason="expired").value
                    == before + 1
                )
            finally:
                tspans.set_enabled(prev)
            assert len(computed) == 1  # the expired call never computed
        finally:
            client.close()


# ---------------------------------------------------------------------------
# the retry budget
# ---------------------------------------------------------------------------


class TestRetryBudget:
    def test_token_bucket_semantics(self):
        from pytensor_federated_tpu.routing import RetryBudget

        b = RetryBudget(rate_per_s=1000.0, burst=2.0)
        assert b.try_spend()
        assert b.try_spend()
        # Burst gone; at 1000/s it refills almost immediately.
        time.sleep(0.01)
        assert b.try_spend()

    def test_denial_is_loud_and_refills(self):
        from pytensor_federated_tpu.routing import RetryBudget

        b = RetryBudget(rate_per_s=50.0, burst=1.0)
        assert b.try_spend(what="hedge")
        assert not b.try_spend(what="hedge")
        assert b.n_denied == 1
        time.sleep(0.05)  # 50/s refill: > 1 token back
        assert b.try_spend(what="hedge")
        snap = b.snapshot()
        assert snap["granted_total"] == 2 and snap["denied_total"] == 1

    def test_validation(self):
        from pytensor_federated_tpu.routing import RetryBudget

        with pytest.raises(ValueError):
            RetryBudget(rate_per_s=0.0)
        with pytest.raises(ValueError):
            RetryBudget(burst=0.5)

    def test_pool_always_has_a_budget(self):
        from pytensor_federated_tpu.routing import NodePool, RetryBudget

        pool = NodePool()
        assert isinstance(pool.retry_budget, RetryBudget)
        assert pool.allow_retry("failover")
        assert "retry_budget" in pool.snapshot()

    def test_exhausted_budget_stops_failover(self):
        """Two dead replicas, burst 1: exactly one failover re-pick is
        granted, then the transport error surfaces — one call never
        sweeps the whole pool once the budget is gone."""
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
            RetryBudget,
        )

        pool = NodePool(
            [("127.0.0.1", 1), ("127.0.0.1", 2), ("127.0.0.1", 3)],
            transport="tcp",
            client_kwargs=dict(
                connect_timeout_s=0.1, connect_retries=0
            ),
            retry_budget=RetryBudget(rate_per_s=0.001, burst=1.0),
        )
        client = PooledArraysClient(pool)
        try:
            with pytest.raises((ConnectionError, OSError)):
                client.evaluate(np.ones(2))
            b = pool.retry_budget
            assert b.n_granted == 1 and b.n_denied == 1
        finally:
            pool.close()

    def test_fanout_member_retry_charges_budget(self):
        from pytensor_federated_tpu.fanout_exec import (
            MemberExecutorPool,
            run_members,
        )
        from pytensor_federated_tpu.routing import NodePool, RetryBudget

        node_pool = NodePool(
            retry_budget=RetryBudget(rate_per_s=0.001, burst=1.0)
        )
        node_pool.member_retries = 5
        calls = []

        def member(sub_inputs, sub_storage):
            calls.append(1)
            raise ConnectionError("transient")

        pool = MemberExecutorPool(1)
        try:
            with pytest.raises(ConnectionError):
                run_members(
                    [member], [0], [1], [], [[None]], pool,
                    node_pool=node_pool,
                )
        finally:
            pool.shutdown()
        # 1 first attempt + exactly 1 budget-granted retry (burst 1),
        # NOT member_retries+1 = 6 attempts.
        assert len(calls) == 2

    def test_spent_deadline_books_neither_success_nor_failure(self):
        """Regression (round-10 review): a pre-send DeadlineExceeded
        from the fail-fast guard says nothing about the replica — it
        was booked as a routing SUCCESS, re-closing half-open breakers
        with phantom traffic under short-deadline overload."""
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )

        pool = NodePool([("127.0.0.1", 1)], transport="tcp")
        client = PooledArraysClient(pool)
        booked = []
        orig = pool.record_result
        pool.record_result = (  # type: ignore[method-assign]
            lambda *a, **k: (booked.append(a), orig(*a, **k))
        )
        try:
            with dl.deadline_scope(0.0):
                with pytest.raises(dl.DeadlineExceeded):
                    client.evaluate(np.ones(1))
            assert booked == []
            # The breaker/probe token went back: still pickable.
            assert pool.pick(1)
        finally:
            pool.close()

    def test_failover_grant_refunded_when_no_replica_remains(self):
        """Regression (round-10 review): a failover token spent just
        before pick() comes back empty amplified nothing — it flows
        back instead of draining the bucket one token per call on a
        single-replica pool."""
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
            RetryBudget,
        )

        pool = NodePool(
            [("127.0.0.1", 1)],
            transport="tcp",
            client_kwargs=dict(
                connect_timeout_s=0.1, connect_retries=0
            ),
            retry_budget=RetryBudget(rate_per_s=0.001, burst=1.0),
        )
        client = PooledArraysClient(pool)
        try:
            with pytest.raises((ConnectionError, OSError)):
                client.evaluate(np.ones(1))
            b = pool.retry_budget
            # Granted (the tally stays) but refunded (the token back).
            assert b.n_granted == 1
            assert b.tokens() >= 0.99
        finally:
            pool.close()

    def test_no_charge_when_failure_requeues_nothing(self, monkeypatch):
        """Regression (round-10 review): a replica that fails AFTER
        serving its whole shard amplifies nothing — charging the
        budget for it drains the bucket faster than actual
        amplification and denies later real failovers early."""
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
            RetryBudget,
        )

        pool = NodePool(
            [("127.0.0.1", 1)],
            transport="tcp",
            retry_budget=RetryBudget(rate_per_s=0.001, burst=1.0),
        )
        client = PooledArraysClient(pool)

        async def fake_window(replica, reqs, window, batch):
            # Every item served, then the transport died late:
            # nothing left to re-queue.
            return (
                [[np.ones(1)] for _ in reqs],
                ConnectionError("late"),
                0.01,
            )

        monkeypatch.setattr(client, "_window_replica", fake_window)
        try:
            res = client.evaluate_many(
                [(np.ones(1),), (np.ones(1),)], window=2
            )
            assert len(res) == 2
            assert pool.retry_budget.n_granted == 0
        finally:
            pool.close()

    def test_round_abort_refunds_granted_tokens(self, monkeypatch):
        """Regression (round-10 review): when a sibling shard's budget
        denial aborts the whole round, tokens granted to the OTHER
        failed shards bought no re-queue — they must flow back (the
        hedge lane's no-replica refund posture)."""
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
            RetryBudget,
        )

        pool = NodePool(
            [("127.0.0.1", 1), ("127.0.0.1", 2)],
            transport="tcp",
            retry_budget=RetryBudget(rate_per_s=0.001, burst=1.0),
        )
        client = PooledArraysClient(pool)

        async def fake_window(replica, reqs, window, batch):
            return [None for _ in reqs], ConnectionError("dead"), 0.01

        monkeypatch.setattr(client, "_window_replica", fake_window)
        try:
            # 4 requests, window 2 -> k=2: BOTH replicas fail with
            # tails in ONE round; the first grant spends the burst,
            # the second is denied and aborts the round.
            with pytest.raises((ConnectionError, OSError)):
                client.evaluate_many([(np.ones(1),)] * 4, window=2)
            b = pool.retry_budget
            assert b.n_granted == 1 and b.n_denied == 1
            # The tallies stay as booked, but the token flowed back.
            assert b.tokens() >= 0.99
        finally:
            pool.close()

    def test_hedge_skipped_when_budget_exhausted(self):
        """An exhausted budget suppresses the hedge instead of firing
        it — checked through the pool's own allow_retry gate."""
        from pytensor_federated_tpu.routing import NodePool, RetryBudget

        pool = NodePool(
            retry_budget=RetryBudget(rate_per_s=0.001, burst=1.0)
        )
        assert pool.allow_retry("hedge")
        assert not pool.allow_retry("hedge")
        assert pool.retry_budget.n_denied == 1


# ---------------------------------------------------------------------------
# the slow_compute fault kind
# ---------------------------------------------------------------------------


class TestSlowComputeKind:
    def test_seeded_bounded_and_replayable(self):
        from pytensor_federated_tpu import faultinject as fi

        def draws(seed):
            plan = fi.FaultPlan(
                [
                    fi.FaultRule(
                        "slow_compute", point="server.compute",
                        every=1, delay_s=0.5,
                    )
                ],
                seed=seed,
            )
            rule = plan.rules[0]
            return [rule.draw_delay_s() for _ in range(5)]

        a, b, c = draws(7), draws(7), draws(8)
        assert a == b  # replayable
        assert a != c  # seeded
        assert all(0.0 <= d <= 0.5 for d in a)  # bounded

    def test_compute_filter_applies_it(self):
        from pytensor_federated_tpu import faultinject as fi
        from pytensor_federated_tpu.faultinject import runtime as fi_rt

        plan = fi.FaultPlan(
            [
                fi.FaultRule(
                    "slow_compute", point="server.compute",
                    nth=1, delay_s=0.05,
                )
            ],
            seed=3,
        )
        fi.install(plan)
        try:
            t0 = time.perf_counter()
            fi_rt.compute_filter()
            assert time.perf_counter() - t0 < 0.2
            assert plan.total_fires == 1
        finally:
            fi.uninstall()

    def test_async_twin_applies_it(self):
        from pytensor_federated_tpu import faultinject as fi
        from pytensor_federated_tpu.faultinject import runtime as fi_rt

        plan = fi.FaultPlan(
            [
                fi.FaultRule(
                    "slow_compute", point="server.compute",
                    nth=1, delay_s=0.05,
                )
            ],
            seed=3,
        )
        fi.install(plan)
        try:
            asyncio.run(fi_rt.compute_filter_async())
            assert plan.total_fires == 1
        finally:
            fi.uninstall()
