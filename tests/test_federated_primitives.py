"""Federated MapReduce primitives + FedAvg (parallel/federated.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.parallel import make_mesh
from pytensor_federated_tpu.parallel.federated import (
    fedavg,
    federated_broadcast,
    federated_map,
    federated_mean,
    federated_sum,
)


@pytest.fixture(scope="module")
def shard_xy():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 64)).astype(np.float32)
    y = (1.0 + 2.0 * x + 0.2 * rng.normal(size=(8, 64))).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


class TestPrimitives:
    def test_map_sum_matches_manual(self, shard_xy):
        x, y = shard_xy
        out = federated_map(lambda d: jnp.sum(d[0] * d[1]), (x, y))
        assert out.shape == (8,)
        np.testing.assert_allclose(
            float(federated_sum(out)), float(jnp.sum(x * y)), rtol=1e-5
        )

    def test_mesh_matches_single_device(self, shard_xy, devices8):
        x, y = shard_xy
        mesh = make_mesh({"shards": 8}, devices=devices8)
        a = federated_map(lambda d: jnp.mean(d[0]), (x, y), mesh=mesh)
        b = federated_map(lambda d: jnp.mean(d[0]), (x, y))
        # rtol ~25x f32 eps: the mesh path's reduction order differs
        # from vmap's, and where it lands within a few ulp varies by
        # XLA version.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-6)

    def test_weighted_mean(self):
        vals = jnp.asarray([[1.0], [3.0]])
        w = jnp.asarray([3.0, 1.0])
        got = federated_mean(vals, w)
        np.testing.assert_allclose(np.asarray(got), [1.5])

    def test_weighted_mean_rejects_wrong_length_weights(self):
        """Regression (ISSUE 6): a wrong-length weights vector that is
        compatible-by-broadcast used to silently weight the wrong axis;
        it must raise instead."""
        vals = jnp.zeros((4, 2))
        # length-1 broadcasts against anything; length-2 broadcasts
        # against the trailing axis after the old reshape — both wrong.
        for bad in (jnp.ones((1,)), jnp.ones((2,)), jnp.ones((4, 1))):
            with pytest.raises(ValueError, match="one weight per shard"):
                federated_mean(vals, bad)
        # the correct length still works
        np.testing.assert_allclose(
            np.asarray(federated_mean(vals, jnp.ones((4,)))),
            np.zeros((2,)),
        )

    def test_broadcast(self):
        out = federated_broadcast({"a": jnp.ones((2,))}, 4)
        assert out["a"].shape == (4, 2)


def _mse(params, shard):
    x, y = shard
    pred = params["a"] + params["b"] * x
    return jnp.mean((y - pred) ** 2)


class TestFedAvg:
    def test_converges_to_pooled_solution(self, shard_xy):
        x, y = shard_xy
        final, history = fedavg(
            _mse,
            (x, y),
            {"a": jnp.zeros(()), "b": jnp.zeros(())},
            rounds=150,
            local_steps=5,
            learning_rate=0.1,
        )
        # iid shards -> FedAvg ~ pooled least squares.
        b_ols, a_ols = np.polyfit(
            np.asarray(x).ravel(), np.asarray(y).ravel(), 1
        )
        assert abs(float(final["a"]) - a_ols) < 0.05
        assert abs(float(final["b"]) - b_ols) < 0.05
        # Loss decreases.
        h = np.asarray(history)
        assert h[-1] < h[0] * 0.1

    def test_mesh_matches_single_device(self, shard_xy, devices8):
        x, y = shard_xy
        mesh = make_mesh({"shards": 8}, devices=devices8)
        kw = dict(rounds=20, local_steps=3, learning_rate=0.1)
        init = {"a": jnp.zeros(()), "b": jnp.zeros(())}
        f_mesh, h_mesh = fedavg(_mse, (x, y), init, mesh=mesh, **kw)
        f_one, h_one = fedavg(_mse, (x, y), init, **kw)
        np.testing.assert_allclose(
            float(f_mesh["a"]), float(f_one["a"]), rtol=2e-3
        )
        np.testing.assert_allclose(
            float(f_mesh["b"]), float(f_one["b"]), rtol=2e-3
        )
        np.testing.assert_allclose(
            np.asarray(h_mesh), np.asarray(h_one), rtol=2e-3
        )

    def test_weighted_by_shard_size(self, shard_xy):
        """Weights shift the fixed point toward the heavy shard."""
        x, y = shard_xy
        # Corrupt shard 0's labels; weight it to near-zero influence.
        y_bad = y.at[0].set(y[0] + 10.0)
        w = jnp.asarray([1e-6] + [1.0] * 7)
        final, _ = fedavg(
            _mse,
            (x, y_bad),
            {"a": jnp.zeros(()), "b": jnp.zeros(())},
            rounds=100,
            local_steps=5,
            learning_rate=0.1,
            weights=w,
        )
        assert abs(float(final["a"]) - 1.0) < 0.1  # not pulled by +10 offset
