"""Censored Weibull AFT: scipy golden, censoring behavior, inference."""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats

from pytensor_federated_tpu.models.survival import (
    FederatedWeibullAFT,
    generate_survival_data,
    weibull_censored_loglik,
)


def test_event_term_matches_scipy():
    rng = np.random.default_rng(0)
    t = rng.weibull(1.5, size=50).astype(np.float32) * 2.0
    eta = rng.normal(0.2, 0.5, size=50).astype(np.float32)
    k = 1.7
    ours = np.asarray(
        weibull_censored_loglik(
            jnp.asarray(t), jnp.ones(50), jnp.asarray(eta), k
        )
    )
    golden = scipy.stats.weibull_min.logpdf(t, k, scale=np.exp(eta))
    np.testing.assert_allclose(ours, golden, rtol=2e-4, atol=2e-4)


def test_censor_term_is_log_survival():
    t = jnp.asarray([0.5, 1.0, 3.0])
    eta = jnp.asarray([0.0, 0.0, 0.0])
    k = 2.0
    ours = np.asarray(
        weibull_censored_loglik(t, jnp.zeros(3), eta, k)
    )
    golden = scipy.stats.weibull_min.logsf(np.asarray(t), k, scale=1.0)
    np.testing.assert_allclose(ours, golden, rtol=1e-4, atol=1e-5)


def test_extreme_proposals_stay_finite():
    t = jnp.asarray([1e-6, 5000.0])
    delta = jnp.asarray([1.0, 0.0])
    X = jnp.asarray([[1.0], [1.0]])

    def lp(w):
        return jnp.sum(
            weibull_censored_loglik(t, delta, X @ w, jnp.exp(3.0))
        )

    for w0 in (-300.0, 300.0):
        v, g = jax.value_and_grad(lp)(jnp.asarray([w0]))
        assert not np.isnan(float(v))
        assert np.all(np.isfinite(np.asarray(g)))


def test_map_recovers_truth():
    data, truth = generate_survival_data(8, n_obs=128, n_features=3, seed=5)
    m = FederatedWeibullAFT(data)
    est = m.find_map()
    np.testing.assert_allclose(np.asarray(est["w"]), truth["w"], atol=0.15)
    k_est = float(jnp.exp(est["log_k"]))
    assert abs(k_est - truth["k"]) < 0.35


def test_ignoring_censoring_biases_scale():
    # Treating censored times as events must bias the scale DOWN
    # (censored times understate survival) — the reason delta exists.
    data, truth = generate_survival_data(
        8, n_obs=128, n_features=2, censor_frac=0.5, seed=8
    )
    m = FederatedWeibullAFT(data)
    est = m.find_map()

    from pytensor_federated_tpu.parallel.packing import ShardedData

    (X, (t, delta)), mask = data.tree()
    data_ignored = ShardedData(
        data=(X, (t, jnp.ones_like(delta))), mask=mask
    )
    m_ignored = FederatedWeibullAFT(data_ignored)
    est_ignored = m_ignored.find_map()
    assert float(est_ignored["b0"]) < float(est["b0"])


def test_nuts_converges():
    data, truth = generate_survival_data(4, n_obs=96, n_features=2, seed=3)
    m = FederatedWeibullAFT(data)
    res = m.sample(
        key=jax.random.PRNGKey(4),
        num_warmup=300,
        num_samples=300,
        num_chains=2,
    )
    summ = res.summary()
    assert float(np.max(np.asarray(summ["rhat"]["w"]))) < 1.1
    w_mean = np.asarray(res.samples["w"]).mean(axis=(0, 1))
    np.testing.assert_allclose(w_mean, truth["w"], atol=0.2)


def test_predictive_and_pointwise():
    data, _ = generate_survival_data(4, n_obs=48, n_features=2, seed=11)
    m = FederatedWeibullAFT(data)
    p0 = m.init_params()
    (X, (t, delta)), mask = data.tree()
    sim = m.predictive(p0, jax.random.PRNGKey(0))
    assert sim.shape == t.shape
    assert np.all(np.asarray(sim)[np.asarray(mask) == 0] == 0.0)
    assert np.all(np.asarray(sim) >= 0.0)
    ll = m.pointwise_loglik(p0)
    assert ll.shape == t.shape
    assert np.all(np.asarray(ll)[np.asarray(mask) == 0] == 0.0)


def test_on_mesh(devices8):
    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"shards": 8}, devices=devices8)
    data, _ = generate_survival_data(8, n_obs=32, n_features=2, seed=9)
    m_mesh = FederatedWeibullAFT(data, mesh=mesh)
    m_local = FederatedWeibullAFT(data)
    p0 = m_local.init_params()
    np.testing.assert_allclose(
        float(m_mesh.logp(p0)), float(m_local.logp(p0)), rtol=5e-4
    )
