"""The shared ELBO core and the SVI lanes (ISSUE 15): batch SVI on
compiled models, and streaming SVI through the gateway under the
deadline regime — sheds skipped, never double-counted.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu import fed, ppl
from pytensor_federated_tpu.ppl import PPLError
from pytensor_federated_tpu.ppl.elbo import (
    gaussian_entropy,
    meanfield_draws,
    scan_vi,
)
from pytensor_federated_tpu.ppl.radon import make_radon_example
from pytensor_federated_tpu.ppl.svi import _classify_skip

optax = pytest.importorskip("optax")


@pytest.fixture(scope="module")
def radon_small():
    model, args, true = make_radon_example(8, mean_obs=8, seed=3)
    return ppl.compile(model, args), true


# ---------------------------------------------------------------------------
# the shared core
# ---------------------------------------------------------------------------


class TestElboCore:
    def test_gaussian_entropy_value(self):
        import math

        dim = 3
        want = dim / 2 * (1 + math.log(2 * math.pi))
        assert float(gaussian_entropy(dim)) == pytest.approx(want)
        assert float(gaussian_entropy(dim, 1.5)) == pytest.approx(
            want + 1.5
        )

    def test_meanfield_draws_shape_and_reparam(self):
        mu = jnp.asarray([1.0, -1.0])
        log_sd = jnp.asarray([0.0, jnp.log(2.0)])
        x = meanfield_draws(mu, log_sd, jax.random.PRNGKey(0), 5000)
        assert x.shape == (5000, 2)
        np.testing.assert_allclose(
            np.asarray(jnp.mean(x, 0)), [1.0, -1.0], atol=0.1
        )
        np.testing.assert_allclose(
            np.asarray(jnp.std(x, 0)), [1.0, 2.0], atol=0.1
        )

    def test_scan_vi_matches_hand_rolled_loop(self):
        """scan_vi is byte-for-byte the loop advi/flows ran: same
        update order, same split stream, same results."""

        def neg_elbo(var, key):
            return jnp.sum((var - 3.0) ** 2) + 0.0 * key[0]

        var0 = jnp.zeros((2,))
        opt = optax.adam(0.1)
        got_var, got_trace = scan_vi(
            neg_elbo, var0, key=jax.random.PRNGKey(0),
            num_steps=25, optimizer=opt,
        )

        var, opt_state = var0, opt.init(var0)
        trace = []
        for k in jax.random.split(jax.random.PRNGKey(0), 25):
            loss, g = jax.value_and_grad(neg_elbo)(var, k)
            updates, opt_state = opt.update(g, opt_state)
            var = optax.apply_updates(var, updates)
            trace.append(-loss)
        np.testing.assert_allclose(
            np.asarray(got_var), np.asarray(var), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(got_trace), np.asarray(jnp.stack(trace)),
            rtol=1e-5,
        )

    def test_advi_reuses_core(self):
        """The satellite contract: samplers/advi.py optimizes through
        the shared core (no second hand-rolled loop)."""
        import inspect

        from pytensor_federated_tpu.samplers import advi, flows

        for mod in (advi, flows):
            src = inspect.getsource(mod)
            assert "scan_vi" in src and "gaussian_entropy" in src
            # no residual hand-rolled optimization loop (docstrings
            # may still SAY "lax.scan" — the call must be gone)
            assert "jax.lax.scan(" not in src


# ---------------------------------------------------------------------------
# batch SVI
# ---------------------------------------------------------------------------


class TestBatchSVI:
    def test_svi_fit_improves_and_recovers(self, radon_small):
        compiled, true = radon_small
        res, unravel = ppl.svi_fit(
            compiled,
            key=jax.random.PRNGKey(0),
            num_steps=400,
            n_mc=4,
            learning_rate=5e-2,
        )
        assert float(res.elbo_trace[-1]) > float(res.elbo_trace[0])
        assert abs(float(res.mean["mu_alpha"]) - true["mu_alpha"]) < 0.8
        draws = res.sample(jax.random.PRNGKey(1), 16, unravel)
        assert draws["alpha_raw"].shape == (16, 8)

    def test_minibatch_svi_runs_and_improves(self, radon_small):
        compiled, _ = radon_small
        res, _ = ppl.svi_fit(
            compiled,
            key=jax.random.PRNGKey(0),
            num_steps=300,
            n_mc=2,
            minibatch=True,
            batch_size=4,
            learning_rate=5e-2,
        )
        # minibatch ELBO estimates are noisy; compare smoothed ends
        first = float(jnp.mean(res.elbo_trace[:50]))
        last = float(jnp.mean(res.elbo_trace[-50:]))
        assert last > first


# ---------------------------------------------------------------------------
# streaming SVI
# ---------------------------------------------------------------------------


class TestClassifySkip:
    def test_deadline(self):
        from pytensor_federated_tpu.service.deadline import (
            DeadlineExceeded,
        )

        assert _classify_skip(DeadlineExceeded("x")) == "shed_deadline"
        # wrapped by the callback layer: TYPE is lost, the in-band
        # string survives
        assert (
            _classify_skip(
                RuntimeError("... deadline exceeded: budget spent ...")
            )
            == "shed_deadline"
        )

    def test_overload(self):
        from pytensor_federated_tpu.gateway.fairness import (
            overload_error,
        )

        exc = RuntimeError(overload_error("svi", "quota"))
        assert _classify_skip(exc) == "shed_overload"

    def test_transient_vs_programming_error(self):
        assert _classify_skip(ConnectionError("boom")) == "failed"
        assert _classify_skip(RuntimeError("node died")) == "failed"
        assert _classify_skip(PPLError("bad model")) is None
        assert _classify_skip(TypeError("bug")) is None
        # the callback layer erases the type; the traceback text
        # still names the deterministic model bug -> must propagate
        assert (
            _classify_skip(
                RuntimeError("...PPLError: duplicate site name 'w'...")
            )
            is None
        )


class TestStreamingSVI:
    def test_local_accounting(self, radon_small):
        compiled, _ = radon_small
        svi = ppl.StreamingSVI(
            compiled, key=jax.random.PRNGKey(0), n_mc=2,
            learning_rate=5e-2,
        )
        rng = np.random.default_rng(0)
        tally = svi.consume(
            rng.choice(8, size=4, replace=False) for _ in range(15)
        )
        assert tally == {"accepted": 15}
        assert svi.offered == svi.accepted == 15
        assert svi.opt_steps == 15  # the optimizer's own counter
        assert len(svi.elbo_trace) == 15
        res, _ = svi.result()
        assert res.flat_mean.shape == svi.mu.shape

    def test_streaming_through_gateway_with_sheds(self, radon_small):
        """The full streaming loop: windows ride the gateway; a
        deadline-starved batch is SHED and provably skipped (the
        optimizer's step counter never moves), then service resumes."""
        from pytensor_federated_tpu.gateway import (
            GatewayThread,
            TenantFairness,
        )
        from pytensor_federated_tpu.routing import NodePool
        from pytensor_federated_tpu.service.tcp import (
            TcpArraysClient,
            serve_tcp_once,
        )

        compiled, _ = radon_small
        ports, evs = [], []
        for _ in range(2):
            ev = threading.Event()
            evs.append(ev)
            threading.Thread(
                target=serve_tcp_once,
                args=(compiled.node_compute(),),
                daemon=True,
                kwargs=dict(
                    ready_callback=lambda p, e=ev: (
                        ports.append(p), e.set()
                    ),
                    concurrent=True,
                ),
            ).start()
        assert all(e.wait(30) for e in evs)
        pool = NodePool(
            [("127.0.0.1", p) for p in ports], transport="tcp"
        )
        pool.start()
        gw = GatewayThread(
            pool, fairness=TenantFairness(), frame_items=16
        )
        gw.start()
        cli = TcpArraysClient("127.0.0.1", gw.port, tenant="svi")
        try:
            pc = ppl.compile(
                compiled.model,
                compiled.model_args,
                placement=fed.PoolPlacement(cli, window=8, tag="svi"),
            )
            svi = ppl.StreamingSVI(
                pc, key=jax.random.PRNGKey(0), n_mc=2,
                learning_rate=5e-2, deadline_s=60.0,
            )
            rng = np.random.default_rng(1)

            def batch():
                return rng.choice(8, size=4, replace=False)

            for _ in range(6):
                assert svi.step(batch()) == "accepted"
            # starve one batch
            svi.deadline_s = 1e-4
            assert svi.step(batch()) == "shed_deadline"
            assert svi.opt_steps == svi.accepted == 6
            # recovery: the shed batch did not poison the lane
            svi.deadline_s = 60.0
            assert svi.step(batch()) == "accepted"
            assert svi.opt_steps == svi.accepted == 7
            assert svi.offered == 8
            assert svi.skipped == {"shed_deadline": 1}
        finally:
            cli.close()
            gw.stop()
            pool.close()

    def test_unclassified_errors_propagate(self, radon_small):
        compiled, _ = radon_small
        svi = ppl.StreamingSVI(compiled, key=jax.random.PRNGKey(0))
        with pytest.raises(PPLError):
            svi.step(np.zeros((2, 2)))  # 2-D batch: a caller bug
        assert svi.accepted == 0 and svi.opt_steps == 0
