"""Fan-out tests: overlap of independent evaluations.

The reference proves its scheduler overlaps work with delay-op timing
assertions (reference: test_op_async.py:98-105, 180-194); the same
technique here — N host nodes that sleep must complete in max, not sum.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from pytensor_federated_tpu import ParallelLogpGrad, fuse, parallel_host_call


def test_fuse_on_device():
    f = lambda x: x + 1.0
    g = lambda a, b: a * b
    fused = fuse([f, g])
    out_f, out_g = fused((jnp.array(1.0),), (jnp.array(2.0), jnp.array(3.0)))
    np.testing.assert_allclose(out_f, 2.0)
    np.testing.assert_allclose(out_g, 6.0)


def _delay_node(delay, scale):
    def host(x):
        time.sleep(delay)
        return [scale * np.asarray(x)]

    return host


def test_parallel_host_call_values():
    spec = (jax.ShapeDtypeStruct((2,), jnp.float32),)
    fn = parallel_host_call(
        [_delay_node(0.0, 2.0), _delay_node(0.0, 3.0)], [spec, spec]
    )
    x = jnp.ones(2)
    (out0,), (out1,) = fn((x,), (x,))
    np.testing.assert_allclose(out0, 2.0)
    np.testing.assert_allclose(out1, 3.0)


def test_parallel_host_call_overlaps():
    """Wall time ~= max(delays), not sum (reference: test_op_async.py:98-105)."""
    delay = 0.4
    spec = (jax.ShapeDtypeStruct((), jnp.float32),)
    n = 4
    fn = parallel_host_call(
        [_delay_node(delay, float(i)) for i in range(n)], [spec] * n
    )
    args = tuple((jnp.float32(1.0),) for _ in range(n))
    fn(*args)  # warm up (compile)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    wall = time.perf_counter() - t0
    assert wall < n * delay * 0.75, f"no overlap: {wall:.2f}s for {n}x{delay}s"


def _quad_node(center):
    def host(x):
        x = np.asarray(x)
        return -np.sum((x - center) ** 2), [-2.0 * (x - center)]

    return host


def test_parallel_logp_grad_values_and_vjp():
    spec = (jax.ShapeDtypeStruct((2,), jnp.float32),)
    op = ParallelLogpGrad([_quad_node(1.0), _quad_node(-1.0)], [spec, spec])
    x = jnp.array([0.0, 2.0])

    results = op([(x,), (x,)])
    np.testing.assert_allclose(results[0][0], -2.0)
    np.testing.assert_allclose(results[1][0], -10.0)

    # Differentiate the sum-of-potentials (reference: demo_model.py:34-36).
    def total(x):
        return op.total_logp([(x,), (x,)])

    g = jax.grad(total)(x)
    expected = -2 * (x - 1.0) + -2 * (x + 1.0)
    np.testing.assert_allclose(g, expected, rtol=1e-6)

    g_jit = jax.jit(jax.grad(total))(x)
    np.testing.assert_allclose(g_jit, expected, rtol=1e-6)


def test_parallel_logp_grad_overlaps():
    delay = 0.4
    n = 3
    spec = (jax.ShapeDtypeStruct((), jnp.float32),)

    def slow_node(i):
        def host(x):
            time.sleep(delay)
            return -float(i) * np.asarray(x) ** 2, [-2 * float(i) * np.asarray(x)]

        return host

    op = ParallelLogpGrad([slow_node(i) for i in range(n)], [spec] * n)
    args = [(jnp.float32(1.0),) for _ in range(n)]
    op(args)  # warm up
    t0 = time.perf_counter()
    jax.block_until_ready(op(args))
    wall = time.perf_counter() - t0
    assert wall < n * delay * 0.75, f"no overlap: {wall:.2f}s"
