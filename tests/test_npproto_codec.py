"""Reference-wire protobuf codec (service/npproto_codec.py).

Three evidence layers that the hand-rolled proto3 framing really is the
reference's wire (reference: protobufs/npproto/ndarray.proto:7-12,
protobufs/service.proto:6-19):

1. GOLDEN BYTES — hand-assembled wire fixtures (tag/varint hex spelled
   out) that the encoder must reproduce exactly and the decoder parse.
2. OFFICIAL-RUNTIME CROSS-CHECK — the same schema built at runtime in
   the installed ``google.protobuf`` (no codegen), asserting
   byte-identical encodes and interchangeable decodes both directions.
3. END-TO-END — a real gRPC round trip: this package's server auto-
   detects an npproto request and replies in kind; the client with
   ``codec="npproto"`` (including GetLoad balancing) gets the same
   numbers the npwire client gets.
4. STAND-IN REFERENCE NODE — a grpc.aio server whose wire handling is
   purely the official google.protobuf runtime (no code from this
   package's codecs on the server side); our npproto client balances,
   streams, and evaluates against it.
"""

import numpy as np
import pytest

from pytensor_federated_tpu.service.npwire import WireError
from pytensor_federated_tpu.service.npproto_codec import (
    GETLOAD_PARAMS,
    decode_arrays_msg,
    decode_arrays_msg_ex,
    decode_get_load_result,
    decode_ndarray,
    encode_arrays_msg,
    encode_get_load_result,
    encode_ndarray,
)

F32_12 = np.array([1.0, 2.5], np.float32)
# field 1 (data, bytes): tag 0x0A, len 8, little-endian f32 payload
# field 2 (dtype, string): tag 0x12, len 7, "float32"
# field 3 (shape, packed int64): tag 0x1A, len 1, varint 2
# field 4 (strides, packed int64): tag 0x22, len 1, varint 4
GOLDEN_F32_12 = bytes.fromhex(
    "0a08" + "0000803f" + "00002040"
    + "1207" + b"float32".hex()
    + "1a01" + "02"
    + "2201" + "04"
)


class TestGoldenBytes:
    def test_ndarray_encode_matches_golden(self):
        assert encode_ndarray(F32_12) == GOLDEN_F32_12

    def test_ndarray_decode_golden(self):
        out = decode_ndarray(GOLDEN_F32_12)
        assert out.dtype == np.float32
        np.testing.assert_array_equal(out, F32_12)

    def test_arrays_msg_golden(self):
        # items: field 1 nested message; uuid: field 2 string "ab"
        golden = (
            bytes([0x0A, len(GOLDEN_F32_12)])
            + GOLDEN_F32_12
            + bytes.fromhex("1202" + b"ab".hex())
        )
        assert encode_arrays_msg([F32_12], uuid="ab") == golden
        arrays, uuid = decode_arrays_msg(golden)
        assert uuid == "ab"
        np.testing.assert_array_equal(arrays[0], F32_12)

    def test_negative_int_ten_byte_varint(self):
        """int32/int64 negatives are 10-byte two's-complement varints
        (NOT zigzag) — the encoding betterproto's int fields use.
        (Negative STRIDES never appear in real reference traffic:
        ``bytes(arr.data)`` requires a contiguous buffer, reference
        npproto/utils.py:13.)  n_clients=-1 is the probe."""
        neg1 = "ffffffffffffffffff01"
        golden = bytes.fromhex("08" + neg1)
        assert encode_get_load_result(-1, 0.0, 0.0) == golden
        assert decode_get_load_result(golden)["n_clients"] == -1

    def test_getload_golden(self):
        # n_clients=3 (varint), percent_cpu=1.5, percent_ram=50.0 (f32)
        golden = bytes.fromhex("0803" + "15" + "0000c03f" + "1d" + "00004842")
        assert encode_get_load_result(3, 1.5, 50.0) == golden
        load = decode_get_load_result(golden)
        assert load == {
            "n_clients": 3,
            "percent_cpu": 1.5,
            "percent_ram": 50.0,
        }
        assert GETLOAD_PARAMS == b""


class TestRoundTrip:
    @pytest.mark.parametrize(
        "arr",
        [
            np.arange(12, dtype=np.float64).reshape(3, 4),
            np.array(3.5, np.float32),  # 0-d
            np.array([], np.int32),  # empty
            np.arange(6, dtype=np.int64).reshape(2, 3).T,  # non-contig
            np.array([True, False]),
            np.array([1 + 2j], np.complex64),
        ],
    )
    def test_ndarray(self, arr):
        out = decode_ndarray(encode_ndarray(arr))
        assert out.dtype == arr.dtype
        np.testing.assert_array_equal(out, arr)

    def test_object_dtype_rejected(self):
        with pytest.raises(WireError, match="object"):
            encode_ndarray(np.array([object()]))

    def test_multi_array_message(self):
        arrays = [np.float64(0.5), np.arange(4, dtype=np.int32)]
        buf = encode_arrays_msg(arrays, uuid="u-1")
        out, uuid = decode_arrays_msg(buf)
        assert uuid == "u-1"
        for a, b in zip(arrays, out):
            np.testing.assert_array_equal(np.asarray(a), b)


class TestWireCompat:
    def test_unpacked_repeated_accepted(self):
        """Parsers must accept unpacked encodings of packed fields."""
        msg = (
            bytes.fromhex("0a04" + "0000803f")
            + bytes.fromhex("1207" + b"float32".hex())
            + bytes.fromhex("18" + "01")  # shape, UNPACKED varint 1
            + bytes.fromhex("20" + "04")  # strides, UNPACKED varint 4
        )
        out = decode_ndarray(msg)
        assert out.shape == (1,) and out[0] == 1.0

    def test_unknown_fields_skipped(self):
        extra = bytes.fromhex("2a03" + "616263")  # field 5, "abc"
        out = decode_ndarray(GOLDEN_F32_12 + extra)
        np.testing.assert_array_equal(out, F32_12)

    @pytest.mark.parametrize(
        "buf",
        [
            bytes.fromhex("0a"),            # truncated length
            bytes.fromhex("0aff"),          # length overruns buffer
            bytes.fromhex("ffffffffffffffffffff01"),  # overlong varint
            bytes.fromhex("0f"),            # illegal wire type 7
            bytes.fromhex("00"),            # field number 0
        ],
    )
    def test_corrupt_raises_wire_error(self, buf):
        with pytest.raises(WireError):
            decode_ndarray(buf)

    def test_inconsistent_shape_raises(self):
        msg = (
            bytes.fromhex("0a04" + "0000803f")  # 4 data bytes
            + bytes.fromhex("1207" + b"float32".hex())
            + bytes.fromhex("1a01" + "63")  # shape [99]
        )
        with pytest.raises(WireError, match="inconsistent"):
            decode_ndarray(msg)


official = pytest.importorskip("google.protobuf", reason="cross-check")


def _official_schema(package="xcheck"):
    """The reference schema rebuilt in the OFFICIAL runtime at runtime
    (no codegen) — THE one schema definition shared by the byte-diff
    cross-check and the stand-in reference node (a drift between two
    copies would let them disagree about what 'the reference wire'
    is).  Returns a name -> message-class getter."""
    from google.protobuf import (
        descriptor_pb2,
        descriptor_pool,
        message_factory,
    )

    pool = descriptor_pool.DescriptorPool()
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = f"{package}.proto"
    fdp.package = package
    fdp.syntax = "proto3"
    F = descriptor_pb2.FieldDescriptorProto

    nd = fdp.message_type.add()
    nd.name = "ndarray"
    for name, num, ftype, label in [
        ("data", 1, F.TYPE_BYTES, F.LABEL_OPTIONAL),
        ("dtype", 2, F.TYPE_STRING, F.LABEL_OPTIONAL),
        ("shape", 3, F.TYPE_INT64, F.LABEL_REPEATED),
        ("strides", 4, F.TYPE_INT64, F.LABEL_REPEATED),
    ]:
        f = nd.field.add()
        f.name, f.number, f.type, f.label = name, num, ftype, label

    for msg_name in ("InputArrays", "OutputArrays"):
        m = fdp.message_type.add()
        m.name = msg_name
        f = m.field.add()
        f.name, f.number, f.type, f.label = (
            "items", 1, F.TYPE_MESSAGE, F.LABEL_REPEATED,
        )
        f.type_name = f".{package}.ndarray"
        f = m.field.add()
        f.name, f.number, f.type, f.label = (
            "uuid", 2, F.TYPE_STRING, F.LABEL_OPTIONAL,
        )

    gl = fdp.message_type.add()
    gl.name = "GetLoadResult"
    for name, num, ftype in [
        ("n_clients", 1, F.TYPE_INT32),
        ("percent_cpu", 2, F.TYPE_FLOAT),
        ("percent_ram", 3, F.TYPE_FLOAT),
    ]:
        f = gl.field.add()
        f.name, f.number, f.type, f.label = name, num, ftype, F.LABEL_OPTIONAL

    pool.Add(fdp)
    return lambda n: message_factory.GetMessageClass(
        pool.FindMessageTypeByName(f"{package}.{n}")
    )


def _official_messages():
    get = _official_schema()
    return get("ndarray"), get("InputArrays"), get("GetLoadResult")


class TestOfficialRuntimeCrossCheck:
    def test_ndarray_bytes_identical(self):
        Nd, _, _ = _official_messages()
        for arr in [
            F32_12,
            np.arange(12, dtype=np.int64).reshape(3, 4),
            np.array([], np.float64),
        ]:
            m = Nd(
                data=bytes(np.ascontiguousarray(arr).data),
                dtype=str(arr.dtype),
                shape=list(arr.shape),
                strides=list(np.ascontiguousarray(arr).strides),
            )
            assert m.SerializeToString(deterministic=True) == encode_ndarray(
                arr
            )

    def test_decode_official_encoding(self):
        Nd, Arrs, _ = _official_messages()
        m = Arrs(uuid="the-uuid")
        item = m.items.add()
        item.CopyFrom(
            Nd(
                data=bytes(F32_12.data),
                dtype="float32",
                shape=[2],
                strides=[4],
            )
        )
        arrays, uuid = decode_arrays_msg(m.SerializeToString())
        assert uuid == "the-uuid"
        np.testing.assert_array_equal(arrays[0], F32_12)

    def test_official_decodes_ours(self):
        _, Arrs, _ = _official_messages()
        buf = encode_arrays_msg(
            [F32_12, np.arange(3, dtype=np.int32)], uuid="u2"
        )
        m = Arrs.FromString(buf)
        assert m.uuid == "u2"
        assert list(m.items[0].shape) == [2]
        assert m.items[1].dtype == "int32"

    def test_getload_bytes_identical(self):
        _, _, GL = _official_messages()
        m = GL(n_clients=3, percent_cpu=1.5, percent_ram=50.0)
        ours = encode_get_load_result(3, 1.5, 50.0)
        assert m.SerializeToString(deterministic=True) == ours
        parsed = GL.FromString(ours)
        assert parsed.n_clients == 3 and parsed.percent_ram == 50.0


# ---------------------------------------------------------------------------
# End-to-end over real gRPC: one server, BOTH wire formats
# ---------------------------------------------------------------------------

NPPROTO_PORT = 29661


def _serve_npproto_node(port):
    import logging

    logging.basicConfig(level=logging.WARNING)
    import numpy as _np

    def compute(x):
        x = _np.asarray(x)
        return [
            _np.asarray(-_np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    from pytensor_federated_tpu.service import run_node

    # Reference-wire GetLoad, so a reference client could balance too;
    # the package's native wait_nodes_up/JSON probe is NOT used below.
    run_node(compute, "127.0.0.1", port, getload_wire="npproto")


def _wait_node_up(port, *, deadline_s=30.0):
    """Poll GetLoad (reply wire auto-detected) until the node answers;
    returns the load dict.  THE one readiness loop for this file."""
    import asyncio
    import time

    from pytensor_federated_tpu.service.client import get_load_async

    deadline = time.time() + deadline_s

    async def up():
        while time.time() < deadline:
            load = await get_load_async("127.0.0.1", port, timeout=1.0)
            if load is not None:
                return load
            await asyncio.sleep(0.2)
        raise TimeoutError(f"node on port {port} did not come up")

    return asyncio.run(up())


class TestEndToEnd:
    @pytest.fixture(scope="class")
    def npproto_node(self):
        from conftest import spawn_node_procs

        procs = spawn_node_procs(_serve_npproto_node, [(NPPROTO_PORT,)])
        yield NPPROTO_PORT
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=5)

    def _wait_up(self, port):
        return _wait_node_up(port)

    def test_npproto_client_roundtrip(self, npproto_node):
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )

        load = self._wait_up(npproto_node)
        assert load["n_clients"] == 0
        client = ArraysToArraysServiceClient(
            "127.0.0.1", npproto_node, codec="npproto"
        )
        x = np.array([1.0, 5.0], np.float64)
        logp, grad = client.evaluate(x)
        np.testing.assert_allclose(float(logp), -8.0)
        np.testing.assert_allclose(grad, [4.0, -4.0])

    def test_same_server_speaks_npwire_too(self, npproto_node):
        """Wire auto-detection: the identical node serves this
        package's native client concurrently."""
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )

        self._wait_up(npproto_node)
        client = ArraysToArraysServiceClient("127.0.0.1", npproto_node)
        x = np.array([3.0, 3.0], np.float64)
        logp, grad = client.evaluate(x)
        np.testing.assert_allclose(float(logp), 0.0)
        np.testing.assert_allclose(grad, [0.0, 0.0])

    def test_npproto_unary_evaluate(self, npproto_node):
        """The reference's primary method is unary Evaluate
        (rpc.py:44-52); exercise it without the stream."""
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )

        self._wait_up(npproto_node)
        client = ArraysToArraysServiceClient(
            "127.0.0.1", npproto_node, codec="npproto", use_stream=False
        )
        x = np.array([2.0], np.float32)
        logp, grad = client.evaluate(x)
        np.testing.assert_allclose(float(logp), -1.0)
        assert grad.dtype == np.float32


def test_structured_dtype_rejected_at_encode_time():
    """str(dtype)/np.dtype() does not round-trip structured dtypes on
    EITHER end of the reference wire — must fail locally and loudly,
    not as a remote gRPC error (review finding)."""
    arr = np.array([(1, 2.0)], dtype=[("a", "<i4"), ("b", "<f8")])
    with pytest.raises(WireError, match="round trip"):
        encode_ndarray(arr)


def test_serve_rejects_two_sources_of_truth():
    import asyncio

    from pytensor_federated_tpu.service import ArraysToArraysService
    from pytensor_federated_tpu.service.server import serve

    svc = ArraysToArraysService(lambda x: [x])
    with pytest.raises(ValueError, match="not both"):
        asyncio.run(serve(lambda x: [x], service=svc))
    with pytest.raises(ValueError, match="compute_fn or a pre-built"):
        asyncio.run(serve(None))


# ---------------------------------------------------------------------------
# Interop against an INDEPENDENT stand-in reference node: a grpc.aio
# server whose wire handling is entirely the OFFICIAL google.protobuf
# runtime (messages built from the reference schema at runtime) — none
# of this package's codecs on the server side.  Our codec="npproto"
# client must interoperate over real gRPC.
# ---------------------------------------------------------------------------


def _serve_official_proto_node(port):
    """A minimal reference-like worker: official-protobuf messages,
    /ArraysToArraysService method paths, unary + lock-step stream +
    GetLoad — independent reimplementation for interop testing."""
    import asyncio

    import grpc
    import numpy as _np

    get = _official_schema("standin")
    Nd, In, Out, GL = (
        get("ndarray"), get("InputArrays"), get("OutputArrays"),
        get("GetLoadResult"),
    )

    def nd_to_np(m):
        return _np.ndarray(
            buffer=m.data, dtype=_np.dtype(m.dtype),
            shape=tuple(m.shape), strides=tuple(m.strides) or None,
        ).copy()

    def np_to_nd(a):
        a = _np.ascontiguousarray(a)
        return Nd(
            data=a.tobytes(), dtype=str(a.dtype),
            shape=list(a.shape), strides=list(a.strides),
        )

    def compute_reply(req_bytes):
        req = In.FromString(req_bytes)
        x = nd_to_np(req.items[0])
        out = Out(uuid=req.uuid)
        o1 = out.items.add()
        o1.CopyFrom(np_to_nd(_np.asarray(-_np.sum((x - 3.0) ** 2))))
        o2 = out.items.add()
        o2.CopyFrom(np_to_nd((-2.0 * (x - 3.0)).astype(x.dtype)))
        return out.SerializeToString()

    async def evaluate(request, context):
        return compute_reply(request)

    async def evaluate_stream(request_iterator, context):
        async for request in request_iterator:
            yield compute_reply(request)

    async def get_load(request, context):
        return GL(n_clients=0, percent_cpu=1.0,
                  percent_ram=2.0).SerializeToString()

    async def main():
        ident = lambda b: b  # noqa: E731
        server = grpc.aio.server()
        handlers = {
            "Evaluate": grpc.unary_unary_rpc_method_handler(
                evaluate, request_deserializer=ident,
                response_serializer=ident,
            ),
            "EvaluateStream": grpc.stream_stream_rpc_method_handler(
                evaluate_stream, request_deserializer=ident,
                response_serializer=ident,
            ),
            "GetLoad": grpc.unary_unary_rpc_method_handler(
                get_load, request_deserializer=ident,
                response_serializer=ident,
            ),
        }
        server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                "ArraysToArraysService", handlers
            ),
        ))
        server.add_insecure_port(f"127.0.0.1:{port}")
        await server.start()
        await server.wait_for_termination()

    asyncio.run(main())


class TestAgainstOfficialProtoServer:
    @pytest.fixture(scope="class")
    def standin_node(self):
        import socket

        from conftest import spawn_node_procs

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = spawn_node_procs(_serve_official_proto_node, [(port,)])
        yield port
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=5)

    def test_client_drives_official_proto_node(self, standin_node):
        """The full interop claim in one test: our npproto client —
        balancing (proto GetLoad auto-detect), lock-step stream, uuid
        correlation — against a server whose wire is purely the
        official protobuf runtime."""
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )

        load = _wait_node_up(standin_node)
        assert load["percent_ram"] == 2.0  # parsed from official bytes

        for use_stream in (True, False):
            client = ArraysToArraysServiceClient(
                "127.0.0.1", standin_node, codec="npproto",
                use_stream=use_stream,
            )
            x = np.array([1.0, 5.0])
            logp, grad = client.evaluate(x)
            np.testing.assert_allclose(float(logp), -8.0)
            np.testing.assert_allclose(grad, [4.0, -4.0])

    def test_pipelined_batch_over_reference_wire(self, standin_node):
        """evaluate_many speaks the reference's protobuf bytes too:
        window-pipelined frames against the official-runtime node,
        replies correlated by the reference's string uuid."""
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
        )

        _wait_node_up(standin_node)
        client = ArraysToArraysServiceClient(
            "127.0.0.1", standin_node, codec="npproto"
        )
        reqs = [
            (np.array([1.0 + i, 5.0 - i]),) for i in range(9)
        ]
        batch = client.evaluate_many(reqs, window=4)
        assert len(batch) == 9
        for (x,), (logp, grad) in zip(
            reqs, [(o[0], o[1]) for o in batch]
        ):
            np.testing.assert_allclose(
                float(np.asarray(logp)), -np.sum((x - 3.0) ** 2)
            )
            np.testing.assert_allclose(
                np.asarray(grad), -2.0 * (x - 3.0)
            )
