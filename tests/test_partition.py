"""The gradient-sharding lane (ISSUE 13): shard math, the partition
wire block on all codecs, sliced/reduced server paths, pooled
reduce-scatter with mid-round failover, and the fed_sum tree lowering.

The contract under test everywhere: partition-free frames stay
byte-identical on every codec; partitioned traffic either produces the
EXACT value or a loud classified error — never a silent partial or
mis-assembled gradient.
"""

import struct
import threading
import time

import numpy as np
import pytest

from pytensor_federated_tpu.routing import partition as gp
from pytensor_federated_tpu.routing.partition import (
    GradPartition,
    PartitionError,
    Reassembler,
    plan_partitions,
)
from pytensor_federated_tpu.service import npproto_codec as npp
from pytensor_federated_tpu.service import npwire
from pytensor_federated_tpu.service.npwire import WireError


# ---------------------------------------------------------------------------
# shard math
# ---------------------------------------------------------------------------


class TestPlan:
    def test_covers_exactly_with_uneven_tail(self):
        plan = plan_partitions(10, 4)
        assert [p.length for p in plan] == [3, 3, 2, 2]
        assert plan[0].offset == 0
        for prev, nxt in zip(plan, plan[1:]):
            assert nxt.offset == prev.offset + prev.length
        assert plan[-1].offset + plan[-1].length == 10
        assert all(p.total == 10 and p.count == 4 for p in plan)

    def test_zero_total_and_single_shard(self):
        assert [p.length for p in plan_partitions(0, 3)] == [0, 0, 0]
        (only,) = plan_partitions(7, 1)
        assert (only.offset, only.length) == (0, 7)

    def test_bad_geometry_is_loud(self):
        with pytest.raises(PartitionError):
            plan_partitions(5, 0)
        with pytest.raises(PartitionError):
            GradPartition(3, 3, 0, 1, 4).validate()  # index == count
        with pytest.raises(PartitionError):
            GradPartition(0, 1, 4, 4, 6).validate()  # overruns total


class TestHeadTailRule:
    def test_slice_reply(self):
        outs = [np.float64(2.5), np.arange(6.0), np.arange(4.0) + 10]
        part = plan_partitions(10, 3)[1]
        head, sl = gp.slice_reply(outs, part)
        np.testing.assert_allclose(head, 2.5)
        np.testing.assert_allclose(
            sl,
            gp.concat_tail(outs)[
                part.offset : part.offset + part.length
            ],
        )

    def test_total_mismatch_is_loud(self):
        with pytest.raises(PartitionError, match="shape disagreement"):
            gp.slice_reply(
                [np.float64(0.0), np.arange(4.0)],
                GradPartition(0, 1, 0, 9, 9),
            )

    def test_mixed_tail_dtype_is_loud(self):
        with pytest.raises(PartitionError, match="share one dtype"):
            gp.tail_layout(
                [np.float64(0), np.zeros(2), np.zeros(2, np.float32)]
            )

    def test_mixed_tail_dtype_names_the_offending_slots(self):
        """ISSUE 19 satellite: the refusal names WHICH reply slot
        carries which dtype (tail slots are reply indices 1..), not
        just the dtype set."""
        with pytest.raises(
            PartitionError,
            match=r"reply\[1\]=float64, reply\[2\]=float32",
        ):
            gp.tail_layout(
                [np.float64(0), np.zeros(2), np.zeros(2, np.float32)]
            )

    def test_split_tail_roundtrip(self):
        outs = [np.float64(0), np.arange(6.0).reshape(2, 3), np.ones(4)]
        flat = gp.concat_tail(outs)
        back = gp.split_tail(flat, [(2, 3), (4,)])
        np.testing.assert_array_equal(back[0], outs[1])
        np.testing.assert_array_equal(back[1], outs[2])
        with pytest.raises(PartitionError):
            gp.split_tail(flat, [(3, 3)])


class TestReduceReplies:
    def test_sum(self):
        a = [np.float64(1.0), np.arange(3.0)]
        b = [np.float64(2.0), np.ones(3)]
        head, tail = gp.reduce_replies([a, b])
        np.testing.assert_allclose(head, 3.0)
        np.testing.assert_allclose(tail, np.arange(3.0) + 1)

    def test_ragged_window_is_loud(self):
        with pytest.raises(PartitionError, match="ragged"):
            gp.reduce_replies(
                [[np.float64(0), np.ones(2)], [np.float64(0)]]
            )
        with pytest.raises(PartitionError, match="silently-casting"):
            gp.reduce_replies(
                [
                    [np.float64(0), np.ones(2)],
                    [np.float64(0), np.ones(3)],
                ]
            )
        with pytest.raises(PartitionError):
            gp.reduce_replies([])


class TestReassembler:
    def test_roundtrip(self):
        flat = np.arange(11.0)
        r = Reassembler(11, 3)
        for p in plan_partitions(11, 3):
            r.add(p, flat[p.offset : p.offset + p.length])
        np.testing.assert_array_equal(r.result(), flat)

    def test_every_anomaly_is_loud(self):
        plan = plan_partitions(10, 4)
        r = Reassembler(10, 4)
        r.add(plan[0], np.zeros(3))
        with pytest.raises(PartitionError, match="duplicate"):
            r.add(plan[0], np.zeros(3))
        with pytest.raises(PartitionError, match="declares length"):
            r.add(plan[1], np.zeros(2))  # wrong slice length
        with pytest.raises(PartitionError, match="geometry"):
            r.add(GradPartition(1, 5, 3, 3, 10), np.zeros(3))
        with pytest.raises(PartitionError, match="overlaps"):
            r.add(GradPartition(1, 4, 2, 3, 10), np.zeros(3))
        with pytest.raises(PartitionError, match="silent cast"):
            r.add(plan[1], np.zeros(3, np.float32))
        with pytest.raises(PartitionError, match="incomplete"):
            r.result()
        assert r.missing == [1, 2, 3]


# ---------------------------------------------------------------------------
# the wire block, all codecs
# ---------------------------------------------------------------------------

PART = (1, 4, 10, 5, 40)


class TestNpwirePartition:
    def test_roundtrip_plain_and_batch(self):
        f = npwire.encode_arrays(
            [np.arange(5.0)], partition=PART, deadline_s=1.0, tenant="t"
        )
        assert npwire.peek_partition(f) == PART
        *_, part, _ver = npwire.decode_arrays_part(f)
        assert part == PART and _ver is None
        b = npwire.encode_batch([f], partition=PART)
        assert npwire.peek_partition(b) == PART
        *_, bpart, _bver = npwire.decode_batch_part(b)
        assert bpart == PART and _bver is None

    def test_absent_is_byte_identical(self):
        a = npwire.encode_arrays([np.arange(3.0)], uuid=b"u" * 16)
        b = npwire.encode_arrays(
            [np.arange(3.0)], uuid=b"u" * 16, partition=None
        )
        assert a == b
        assert npwire.peek_partition(a) is None

    def test_historical_decoders_drop_the_block(self):
        f = npwire.encode_arrays([np.arange(3.0)], partition=PART)
        arrays, _uid, err = npwire.decode_arrays(f)
        assert err is None
        np.testing.assert_array_equal(arrays[0], np.arange(3.0))

    def test_invalid_block_is_loud_at_encode(self):
        with pytest.raises(WireError):
            npwire.encode_arrays([], partition=(4, 4, 0, 0, 0))
        with pytest.raises(WireError):
            npwire.encode_arrays([], partition=(0, 1, 3, 3, 4))

    def test_truncated_block_is_loud(self):
        f = npwire.encode_arrays([], uuid=b"u" * 16, partition=PART)
        # cut inside the partition block (header is 26 bytes)
        with pytest.raises(WireError, match="partition"):
            npwire.decode_arrays_part(f[:30])
        with pytest.raises(WireError, match="partition"):
            npwire.peek_partition(f[:30])


class TestNpprotoPartition:
    def test_roundtrip(self):
        msg = npp.encode_arrays_msg(
            [np.ones(2)], uuid="u", partition=PART
        )
        assert npp.peek_partition_msg(msg) == PART
        arrays, uuid, err, _tid, _sp = npp.decode_arrays_msg_full(msg)
        assert uuid == "u" and err is None
        bmsg = npp.encode_batch_msg([msg], uuid="w", partition=PART)
        assert npp.peek_partition_msg(bmsg) == PART
        items, wuuid, _t, _s = npp.decode_batch_msg(bmsg)
        assert wuuid == "w" and len(items) == 1

    def test_absent_is_byte_identical(self):
        a = npp.encode_arrays_msg([np.ones(2)], uuid="u")
        b = npp.encode_arrays_msg([np.ones(2)], uuid="u", partition=None)
        assert a == b
        assert npp.peek_partition_msg(a) is None

    def test_reference_runtime_skips_field_20(self):
        """An unmodified reference peer (official protobuf runtime)
        parses a message carrying field 20 and sees the same
        items/uuid — the proto3 forward-compatibility contract."""
        pytest.importorskip("google.protobuf")
        from google.protobuf import descriptor_pb2, descriptor_pool
        from google.protobuf import message_factory

        fdp = descriptor_pb2.FileDescriptorProto()
        fdp.name = "ref_partition.proto"
        fdp.syntax = "proto3"
        msg_t = fdp.message_type.add()
        msg_t.name = "InputArrays"
        item_f = msg_t.field.add()
        item_f.name = "items"
        item_f.number = 1
        item_f.type = descriptor_pb2.FieldDescriptorProto.TYPE_BYTES
        item_f.label = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
        uuid_f = msg_t.field.add()
        uuid_f.name = "uuid"
        uuid_f.number = 2
        uuid_f.type = descriptor_pb2.FieldDescriptorProto.TYPE_STRING
        uuid_f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
        pool = descriptor_pool.DescriptorPool()
        pool.Add(fdp)
        cls = message_factory.GetMessageClass(
            pool.FindMessageTypeByName("InputArrays")
        )
        wire = npp.encode_arrays_msg(
            [np.ones(2)], uuid="ref-check", partition=PART
        )
        parsed = cls.FromString(wire)
        assert parsed.uuid == "ref-check"
        assert len(parsed.items) == 1  # field 20 skipped by wire type


class TestShmPartition:
    def test_roundtrip_and_byte_identical(self):
        from pytensor_federated_tpu.service import shm

        bare = shm.encode_frame(shm._KIND_EVAL, b"u" * 16, b"body")
        same = shm.encode_frame(
            shm._KIND_EVAL, b"u" * 16, b"body", partition=None
        )
        assert bare == same
        stamped = shm.encode_frame(
            shm._KIND_EVAL, b"u" * 16, b"body", partition=PART,
            deadline_s=2.0,
        )
        k, u, e, t, d, part, _ver, off, frame = shm.decode_frame(stamped)
        assert part == PART and d == 2.0
        assert frame[off:] == b"body"
        k, u, e, t, d, part, _ver, off, frame = shm.decode_frame(bare)
        assert part is None

    def test_truncated_block_is_loud(self):
        from pytensor_federated_tpu.service import shm

        stamped = shm.encode_frame(
            shm._KIND_EVAL, b"u" * 16, partition=PART
        )
        with pytest.raises(WireError, match="partition"):
            shm.decode_frame(stamped[:-4])

    def test_undeclared_flag_still_rejected(self):
        from pytensor_federated_tpu.service import shm

        frame = bytearray(shm.encode_frame(shm._KIND_EVAL, b"u" * 16))
        frame[6] |= 0x40  # first bit past VERSION (32)
        with pytest.raises(WireError, match="unknown shm flag"):
            shm.decode_frame(bytes(frame))


# ---------------------------------------------------------------------------
# server paths: sliced replies + reduce windows
# ---------------------------------------------------------------------------


def _quad_compute(x, y):
    x = np.asarray(x)
    y = np.asarray(y)
    return [
        np.asarray(np.sum((x - y) ** 2)),
        2.0 * (x - y),
        -2.0 * (x - y),
    ]


def _start_tcp(compute):
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    holder = {}
    ready = threading.Event()
    threading.Thread(
        target=serve_tcp_once,
        args=(compute,),
        kwargs=dict(
            port=0,
            ready_callback=lambda p: (holder.update(p=p), ready.set()),
            concurrent=True,
        ),
        daemon=True,
    ).start()
    assert ready.wait(10)
    return holder["p"]


def _start_shm(compute):
    from pytensor_federated_tpu.service.shm import serve_shm

    holder = {}
    ready = threading.Event()
    threading.Thread(
        target=serve_shm,
        args=(compute,),
        kwargs=dict(
            port=0,
            ready_callback=lambda p: (holder.update(p=p), ready.set()),
        ),
        daemon=True,
    ).start()
    assert ready.wait(10)
    return holder["p"]


@pytest.fixture(scope="module")
def tcp_port():
    return _start_tcp(_quad_compute)


@pytest.fixture(scope="module")
def shm_port():
    return _start_shm(_quad_compute)


def _reference_sums(reqs):
    head = np.sum([_quad_compute(*r)[0] for r in reqs])
    flat = np.sum(
        [gp.concat_tail(_quad_compute(*r)) for r in reqs], axis=0
    )
    return head, flat


class TestServerReduce:
    def _reqs(self, n=10, size=8, seed=0):
        rng = np.random.default_rng(seed)
        return [
            (rng.normal(size=size), rng.normal(size=size))
            for _ in range(n)
        ]

    @pytest.mark.parametrize("slices", [1, 3])
    def test_tcp_reduce_equals_local_sum(self, tcp_port, slices):
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        client = TcpArraysClient("127.0.0.1", tcp_port)
        reqs = self._reqs()
        want_head, want_flat = _reference_sums(reqs)
        head, flat = client.evaluate_reduced(
            reqs, window=4, slices=slices, total=16
        )
        np.testing.assert_allclose(head, want_head, rtol=1e-12)
        np.testing.assert_allclose(flat, want_flat, rtol=1e-12)
        client.close()

    @pytest.mark.parametrize("slices", [1, 3])
    def test_shm_reduce_equals_local_sum(self, shm_port, slices):
        from pytensor_federated_tpu.service.shm import ShmArraysClient

        client = ShmArraysClient("127.0.0.1", shm_port)
        reqs = self._reqs(seed=1)
        want_head, want_flat = _reference_sums(reqs)
        head, flat = client.evaluate_reduced(
            reqs, window=4, slices=slices, total=16
        )
        np.testing.assert_allclose(head, want_head, rtol=1e-12)
        np.testing.assert_allclose(flat, want_flat, rtol=1e-12)
        # The doorbell stays correlated for ordinary traffic after.
        out = client.evaluate(*reqs[0])
        np.testing.assert_allclose(out[0], _quad_compute(*reqs[0])[0])
        client.close()

    def test_total_mismatch_is_in_band_loud(self, tcp_port):
        from pytensor_federated_tpu.service.tcp import (
            RemoteComputeError,
            TcpArraysClient,
        )

        client = TcpArraysClient("127.0.0.1", tcp_port)
        with pytest.raises(
            RemoteComputeError, match="shape disagreement"
        ):
            client.evaluate_reduced(
                self._reqs(n=2), window=2, slices=1, total=99
            )
        client.close()

    def test_reduce_is_all_or_nothing(self, tcp_port):
        """A poisoned item fails the WHOLE window in-band — summing
        around it would be the silent partial sum the loud-reassembly
        contract forbids."""
        from pytensor_federated_tpu.service.tcp import (
            RemoteComputeError,
            TcpArraysClient,
        )

        client = TcpArraysClient("127.0.0.1", tcp_port)
        reqs = self._reqs(n=3)
        reqs[1] = (np.zeros(8), np.zeros(3))  # shape mismatch inside
        with pytest.raises(RemoteComputeError):
            client.evaluate_reduced(reqs, window=4, slices=1, total=16)
        client.close()

    def test_sliced_plain_request(self, tcp_port):
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        client = TcpArraysClient("127.0.0.1", tcp_port)
        x, y = np.arange(8.0), np.ones(8)
        full = client.evaluate(x, y)
        part = GradPartition(1, 4, 4, 4, 16)
        head, sl = client.evaluate(x, y, partition=part)
        np.testing.assert_allclose(head, full[0])
        np.testing.assert_allclose(
            sl, gp.concat_tail(full)[4:8]
        )
        client.close()

    def test_partitioned_caller_reassembles(self, tcp_port):
        from pytensor_federated_tpu.fanout_exec import PartitionedCaller
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        client = TcpArraysClient("127.0.0.1", tcp_port)
        pc = PartitionedCaller(
            client, total=16, max_slice_elems=5,
            tail_shapes=[(8,), (8,)],
        )
        assert pc.count == 4
        x, y = np.arange(8.0), np.full(8, 2.0)
        out = pc.evaluate(x, y)
        ref = _quad_compute(x, y)
        for got, want in zip(out, ref):
            np.testing.assert_allclose(got, want)
        client.close()


# ---------------------------------------------------------------------------
# pooled reduce-scatter: mixed transports + mid-round failover + budget
# ---------------------------------------------------------------------------


class TestPooledReduce:
    def test_mixed_transport_pool(self, tcp_port, shm_port):
        """tcp + shm replicas in ONE pool under partitioned replies
        (the grpc fallback lane is covered by the unit test below —
        spinning an aio server inside this suite flakes on loop
        teardown)."""
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )

        pool = NodePool([("127.0.0.1", tcp_port)], transport="tcp")
        pool.add_replica("127.0.0.1", shm_port, transport="shm")
        client = PooledArraysClient(pool)
        rng = np.random.default_rng(7)
        reqs = [
            (rng.normal(size=8), rng.normal(size=8)) for _ in range(16)
        ]
        want_head, want_flat = _reference_sums(reqs)
        head, flat = client.evaluate_reduced(reqs, window=4, total=16)
        np.testing.assert_allclose(head, want_head, rtol=1e-12)
        np.testing.assert_allclose(flat, want_flat, rtol=1e-12)
        pool.close()

    def test_failover_requeues_only_missing_shard(self, tcp_port):
        """One replica dead mid-round: its shard re-queues onto the
        survivor, the retry budget is charged exactly once (the PR-10
        evaluate_many refund posture), and the sums stay exact."""
        import socket as socket_mod

        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )

        # A port that refuses connections: reserve-and-close.
        s = socket_mod.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()

        pool = NodePool(
            [("127.0.0.1", tcp_port), ("127.0.0.1", dead_port)],
            transport="tcp",
            client_kwargs=dict(
                connect_timeout_s=1.0, connect_retries=0
            ),
        )
        client = PooledArraysClient(pool)
        rng = np.random.default_rng(8)
        reqs = [
            (rng.normal(size=8), rng.normal(size=8)) for _ in range(12)
        ]
        want_head, want_flat = _reference_sums(reqs)
        before = pool.retry_budget.snapshot()["granted_total"]
        head, flat = client.evaluate_reduced(reqs, window=6, total=16)
        np.testing.assert_allclose(head, want_head, rtol=1e-12)
        np.testing.assert_allclose(flat, want_flat, rtol=1e-12)
        after = pool.retry_budget.snapshot()["granted_total"]
        # At most one charge per failed replica WITH a tail (not one
        # per re-queued request) — and the dead replica fails every
        # pick, so at least one charge happened.
        assert 1 <= after - before <= 2
        pool.close()

    def test_grpc_fallback_reduces_driver_side(self):
        """A grpc replica (no reduce wire) reduces on the DRIVER via
        evaluate_many_partial — unit-tested against a stub replica so
        the mixed-pool contract is covered without an aio server."""
        import asyncio

        from pytensor_federated_tpu.routing.pooled_client import (
            PooledArraysClient,
        )
        from pytensor_federated_tpu.routing import NodePool

        reqs = [(np.arange(4.0) + i,) for i in range(5)]
        replies = [
            [np.asarray(float(i)), np.arange(4.0) + i, 2 * np.arange(4.0)]
            for i in range(5)
        ]

        class StubGrpcClient:
            async def evaluate_many_partial_async(
                self, requests, *, window, batch
            ):
                return [replies[i] for i in range(len(requests))], None

        pool = NodePool([("127.0.0.1", 1)], transport="grpc")
        replica = pool.replicas[0]
        replica.client = StubGrpcClient()
        client = PooledArraysClient(pool)
        head, flat = asyncio.run(
            client.evaluate_reduced_async(reqs, window=8, total=8)
        )
        want = gp.reduce_replies(replies)
        np.testing.assert_allclose(head, want[0])
        np.testing.assert_allclose(
            flat, gp.concat_tail(want)
        )
        pool.close()


# ---------------------------------------------------------------------------
# tree aggregation (mid-tier nodes)
# ---------------------------------------------------------------------------


class TestTreeAggregation:
    def test_two_level_tree_exact(self, tcp_port):
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
            make_aggregator_compute,
        )

        leaf2 = _start_tcp(_quad_compute)
        mids = []
        for leaf in (tcp_port, leaf2):
            child_pool = NodePool(
                [("127.0.0.1", leaf)], transport="tcp"
            )
            child = PooledArraysClient(child_pool)
            mids.append(
                _start_tcp(make_aggregator_compute(child, window=4))
            )
        pool = NodePool(
            [("127.0.0.1", p) for p in mids], transport="tcp"
        )
        client = PooledArraysClient(pool)
        rng = np.random.default_rng(9)
        reqs = [
            (rng.normal(size=8), rng.normal(size=8)) for _ in range(12)
        ]
        want_head, want_flat = _reference_sums(reqs)
        head, flat = client.evaluate_reduced(reqs, window=6, total=16)
        np.testing.assert_allclose(head, want_head, rtol=1e-12)
        np.testing.assert_allclose(flat, want_flat, rtol=1e-12)
        pool.close()


# ---------------------------------------------------------------------------
# chaos: shard faults surface loudly
# ---------------------------------------------------------------------------


class TestShardFaultsLoud:
    @pytest.mark.parametrize(
        "kind", ["drop_shard", "dup_shard", "corrupt_shard"]
    )
    def test_tcp_reduce_reply_faults(self, kind):
        from pytensor_federated_tpu import faultinject as fi
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        port = _start_tcp(_quad_compute)
        reqs = [(np.arange(4.0), np.ones(4)) for _ in range(4)]
        fi.install(
            fi.FaultPlan(
                [fi.FaultRule(kind, point="partition.reply", nth=1)],
                seed=3,
            )
        )
        try:
            client = TcpArraysClient("127.0.0.1", port, retries=0)
            with pytest.raises((WireError, RuntimeError)):
                client.evaluate_reduced(
                    reqs, window=4, slices=3, total=8
                )
            client.close()
        finally:
            fi.uninstall()

    @pytest.mark.parametrize("kind", ["drop_shard", "dup_shard"])
    def test_shm_reduce_reply_faults(self, kind):
        from pytensor_federated_tpu import faultinject as fi
        from pytensor_federated_tpu.service.shm import ShmArraysClient

        port = _start_shm(_quad_compute)
        reqs = [(np.arange(4.0), np.ones(4)) for _ in range(4)]
        fi.install(
            fi.FaultPlan(
                [fi.FaultRule(kind, point="partition.reply", nth=1)],
                seed=4,
            )
        )
        try:
            client = ShmArraysClient("127.0.0.1", port, retries=0)
            with pytest.raises((WireError, RuntimeError)):
                client.evaluate_reduced(
                    reqs, window=4, slices=2, total=8
                )
            client.close()
        finally:
            fi.uninstall()


# ---------------------------------------------------------------------------
# fed lowering: the reduced fed_sum(fed_map) pair
# ---------------------------------------------------------------------------


class TestFedReduceLowering:
    def _make(self, reduce, n_shards=6, n_pts=16):
        import jax.numpy as jnp

        from pytensor_federated_tpu.fed.lowering import FederatedLogpGrad
        from pytensor_federated_tpu.fed.placements import PoolPlacement
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        rng = np.random.default_rng(11)
        data = {
            "x": jnp.asarray(rng.normal(size=(n_shards, n_pts))),
            "y": jnp.asarray(rng.normal(size=(n_shards, n_pts))),
        }

        def per_shard(a, b, shard):
            resid = shard["y"] - (a + b * shard["x"])
            return -0.5 * jnp.sum(resid ** 2)

        dense = FederatedLogpGrad(per_shard, data)
        port = _start_tcp(dense.node_compute())
        placement = PoolPlacement(
            TcpArraysClient("127.0.0.1", port),
            window=4,
            reduce=reduce,
        )
        pooled = FederatedLogpGrad(per_shard, data, placement=placement)
        return dense, pooled

    def test_reduced_grad_equals_dense(self):
        import jax.numpy as jnp

        from pytensor_federated_tpu.telemetry import flightrec

        flightrec.set_enabled(True)
        flightrec.clear()
        dense, pooled = self._make(reduce=True)
        a0, b0 = jnp.asarray(0.3), jnp.asarray(-0.7)
        lp_ref, g_ref = dense.logp_and_grad(a0, b0)
        lp, g = pooled.logp_and_grad(a0, b0)
        np.testing.assert_allclose(float(lp), float(lp_ref), rtol=1e-5)
        for got, want in zip(g, g_ref):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5
            )
        # The reduce lane actually served it (not the per-shard lane).
        assert any(
            e["kind"] == "fed.reduce_window" for e in flightrec.events()
        )
        # Eager logp matches too.
        np.testing.assert_allclose(
            float(pooled.logp(a0, b0)), float(lp_ref), rtol=1e-5
        )

    def test_per_shard_input_gates_out_of_reduce(self):
        """A fed_map whose inexact mapped operand is a PROGRAM INPUT
        (per-shard data passed as an argument) must fall back to the
        per-shard window: the summed gradient cannot stand in for
        per-shard cotangents of a non-broadcast consumer."""
        import jax
        import jax.numpy as jnp

        from pytensor_federated_tpu import fed
        from pytensor_federated_tpu.fed.placements import PoolPlacement
        from pytensor_federated_tpu.fed.placements import (
            make_node_compute,
        )
        from pytensor_federated_tpu.telemetry import flightrec

        n_shards = 4

        def per_shard_flat(theta, x):
            return -0.5 * jnp.sum((x - theta) ** 2)

        port = _start_tcp(make_node_compute(per_shard_flat))

        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        placement = PoolPlacement(
            TcpArraysClient("127.0.0.1", port), window=4, reduce=True
        )

        def model(theta, data):
            pb = fed.fed_broadcast(theta, n_shards)
            lps = fed.fed_map(
                lambda s: per_shard_flat(s[0], s[1]), (pb, data)
            )
            return fed.fed_sum(lps)

        prog = fed.program(model, placement)
        rng = np.random.default_rng(13)
        data = jnp.asarray(rng.normal(size=(n_shards, 8)))
        theta = jnp.asarray(0.4)

        flightrec.set_enabled(True)
        flightrec.clear()
        got = prog(theta, data)
        want = model(theta, data)  # dense semantics
        np.testing.assert_allclose(float(got), float(want), rtol=1e-9)
        # The gate held: the per-shard window served it, NOT reduce.
        kinds = {e["kind"] for e in flightrec.events()}
        assert "fed.reduce_window" not in kinds
        # And the gradient w.r.t. the per-shard DATA is exact.
        g_got = jax.grad(prog, argnums=1)(theta, data)
        g_want = jax.grad(model, argnums=1)(theta, data)
        np.testing.assert_allclose(
            np.asarray(g_got), np.asarray(g_want), rtol=1e-9
        )


# ---------------------------------------------------------------------------
# fleet SLO: the partition-aware goodput clamp
# ---------------------------------------------------------------------------


class TestSloPartitionClamp:
    def _snapshot(self, ts, requests, errors, shards, shard_errors):
        class Scrape:
            ok = True

            def __init__(self, metrics):
                self.metrics = metrics

        def counter(value, labels=None):
            return {
                "children": [
                    {"labels": labels or {}, "value": value}
                ]
            }

        metrics = {
            "pftpu_server_requests_total": {
                "children": [
                    {
                        "labels": {"method": "evaluate_reduce"},
                        "value": requests,
                    }
                ]
            },
            "pftpu_server_errors_total": counter(errors),
            "pftpu_admission_shed_total": counter(0.0),
            "pftpu_partition_shards_total": {
                "children": [
                    {
                        "labels": {"outcome": "ok"},
                        "value": shards - shard_errors,
                    },
                    {
                        "labels": {"outcome": "error"},
                        "value": shard_errors,
                    },
                ]
            },
            "pftpu_client_call_seconds": {"children": []},
        }

        class Snap:
            pass

        snap = Snap()
        snap.ts = ts
        snap.replicas = {"n1:1": Scrape(metrics)}
        return snap

    def test_zero_frame_replica_with_shard_errors_is_not_healthy(self):
        from pytensor_federated_tpu.telemetry.slo import (
            BurnRateEngine,
            Slo,
        )

        engine = BurnRateEngine(
            Slo(name="t", goodput_min=1.0), windows_s=(10.0,)
        )
        engine.observe(self._snapshot(0.0, 10.0, 0.0, 0.0, 0.0))
        # Window 2: frames counted ZERO new requests... but the
        # replica refused 5 partition shards (errors grew too).  The
        # old clamp min(err_d, req_d=0) folded this to healthy.
        report = engine.observe(self._snapshot(5.0, 10.0, 5.0, 5.0, 5.0))
        win = report["windows"]["10s"]
        assert win["errors"] == 5.0  # clamped at req_d + shard_err_d
        assert win["shard_errors"] == 5.0

    def test_shard_error_delta_clamped_at_shard_requests(self):
        from pytensor_federated_tpu.telemetry.slo import (
            BurnRateEngine,
            Slo,
        )

        engine = BurnRateEngine(
            Slo(name="t", goodput_min=1.0), windows_s=(10.0,)
        )
        engine.observe(self._snapshot(0.0, 0.0, 0.0, 0.0, 0.0))
        # shard_errors delta (7) exceeds shard delta (3): the mirror
        # of the PR-11 frame clamp caps it at the shard request delta.
        report = engine.observe(self._snapshot(5.0, 4.0, 0.0, 3.0, 7.0))
        win = report["windows"]["10s"]
        assert win["shard_errors"] == 3.0

    def test_evaluate_reduce_counts_as_requests(self):
        from pytensor_federated_tpu.telemetry.slo import (
            BurnRateEngine,
            Slo,
        )

        engine = BurnRateEngine(
            Slo(name="t", goodput_min=0.5), windows_s=(10.0,)
        )
        engine.observe(self._snapshot(0.0, 0.0, 0.0, 0.0, 0.0))
        report = engine.observe(self._snapshot(5.0, 20.0, 0.0, 0.0, 0.0))
        win = report["windows"]["10s"]
        assert win["requests"] == 20.0
        assert win["burn_rate"] is not None and win["burn_rate"] < 1.0
