"""Pathfinder VI (samplers/pathfinder.py).

Oracle 1: Gaussian targets, where the BFGS curvature recovers the exact
covariance and the ELBO-best fit must match the true moments.  Oracle 2:
the federated linear-regression posterior, cross-checked against the
Laplace approximation (itself NUTS-checked in test_laplace.py).
"""

import jax
import jax.numpy as jnp
import numpy as np

from pytensor_federated_tpu.samplers import (
    laplace_approximation,
    multipath_pathfinder,
    pathfinder,
)


class TestGaussianTarget:
    def test_recovers_moments(self):
        A = jnp.asarray([[2.0, 0.6], [0.6, 1.5]])
        mu = jnp.asarray([1.0, -1.0])

        def logp(p):
            d = p["x"] - mu
            return -0.5 * d @ A @ d

        res = pathfinder(
            logp,
            {"x": jnp.zeros(2)},
            jax.random.PRNGKey(0),
            num_steps=300,
            num_draws=4000,
        )
        # VI-grade: where along the L-BFGS path the ELBO argmax lands
        # (hence the fitted mean) shifts a little with XLA version.
        np.testing.assert_allclose(
            np.asarray(res.mean_flat), np.asarray(mu), atol=0.2
        )
        # VI-grade covariance accuracy (the windowed-BFGS fit is an
        # approximation, not the exact Hessian inverse).
        np.testing.assert_allclose(
            np.asarray(res.cov_flat),
            np.linalg.inv(np.asarray(A)),
            atol=0.25,
        )
        # Draws center on the FITTED mean, so this inherits the fitted
        # mean's version-dependent shift plus Monte Carlo error.
        emp_mean = jnp.mean(res.samples["x"], axis=0)
        np.testing.assert_allclose(
            np.asarray(emp_mean), np.asarray(mu), atol=0.25
        )
        assert float(res.elbo) > -2.0  # ~ -H[q] for a near-exact fit

    def test_isotropic_converges_in_one_linesearch(self):
        """On N(0, I) the very first L-BFGS line-search step lands on
        the optimum (H0 = I is exact), so the selected fit — whichever
        iterate wins — must already be the exact posterior."""

        def logp(p):
            return -0.5 * jnp.sum(p["x"] ** 2)

        res = pathfinder(
            logp,
            {"x": 3.0 * jnp.ones(3)},
            jax.random.PRNGKey(1),
            num_steps=200,
        )
        np.testing.assert_allclose(
            np.asarray(res.mean_flat), np.zeros(3), atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(res.cov_flat), np.eye(3), atol=0.1
        )
        assert np.isfinite(float(res.elbo))


class TestDegenerate:
    def test_stationary_start_raises(self):
        """Starting exactly at the mode gives a zero-length path with
        no curvature pairs — must fail loudly, not return N(mode, I)."""

        def logp(p):
            return -0.5 * jnp.sum(p["x"] ** 2)

        with np.testing.assert_raises(ValueError):
            pathfinder(
                logp,
                {"x": jnp.zeros(3)},
                jax.random.PRNGKey(7),
                num_steps=50,
            )


class TestFederatedPosterior:
    def test_agrees_with_laplace(self):
        from pytensor_federated_tpu.models.linear import (
            FederatedLinearRegression,
            generate_node_data,
        )

        data, _ = generate_node_data(4, n_obs=64, seed=3)
        model = FederatedLinearRegression(data)
        lap = laplace_approximation(
            model.logp, model.init_params(), num_steps=1500
        )
        res = pathfinder(
            model.logp,
            model.init_params(),
            jax.random.PRNGKey(2),
            num_steps=400,
            num_draws=2000,
        )
        # Means agree tightly; marginal sds within 30%.
        np.testing.assert_allclose(
            np.asarray(res.mean_flat),
            np.asarray(lap.mean_flat),
            atol=0.05,
        )
        np.testing.assert_allclose(
            np.sqrt(np.diag(np.asarray(res.cov_flat))),
            np.sqrt(np.diag(np.asarray(lap.cov_flat))),
            rtol=0.3,
        )

    def test_multipath(self):
        def logp(p):
            return -0.5 * jnp.sum((p["x"] - 2.0) ** 2)

        res = multipath_pathfinder(
            logp,
            {"x": jnp.zeros(2)},
            jax.random.PRNGKey(4),
            num_paths=3,
            num_steps=150,
            num_draws=900,
        )
        assert res.samples["x"].shape == (900, 2)
        np.testing.assert_allclose(
            np.asarray(jnp.mean(res.samples["x"], axis=0)),
            [2.0, 2.0],
            atol=0.15,
        )
