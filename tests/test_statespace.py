"""Linear-Gaussian state-space models (models/statespace.py).

Golden model at two levels (pattern from test_demo_node.py:29-65 in the
reference): (1) the exact joint-Gaussian marginal likelihood computed by
building the full TxT observation covariance — ground truth for the
sequential filter; (2) the sequential filter — ground truth for the
associative-scan and sequence-sharded paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.models.statespace import (
    SeqShardedLGSSM,
    generate_lgssm_data,
    kalman_logp_parallel,
    kalman_logp_seq,
    kalman_smoother_parallel,
    kalman_smoother_seq,
)
from pytensor_federated_tpu.parallel import make_mesh


def dense_joint_moments(params, T):
    """Exact joint latent moments (means list, covz[s, t]) built
    densely — O(T^2 d^2) memory, only viable for tiny T."""
    F = np.asarray(params["F"], np.float64)
    d = F.shape[0]
    Q = np.exp(float(params["log_q"])) * np.eye(d)
    m0 = np.asarray(params["m0"], np.float64)
    P0 = np.eye(d)
    # Latent joint moments via the recursion z_t = F z_{t-1} + w_t.
    means = []
    m = m0
    for _ in range(T):
        m = F @ m
        means.append(m)
    # Cov[z_s, z_t] built forward.
    covz = np.zeros((T, T, d, d))
    Pprev = P0
    for t in range(T):
        Pt = F @ Pprev @ F.T + Q
        covz[t, t] = Pt
        for s in range(t + 1, T):
            covz[t, s] = covz[t, s - 1] @ F.T
            covz[s, t] = covz[t, s].T
        Pprev = Pt
    return means, covz


def dense_joint_logp(params, y):
    """Exact marginal: y ~ N(mu, Sigma) from the dense joint moments."""
    H = np.asarray(params["H"], np.float64)
    k = H.shape[0]
    T = y.shape[0]
    means, covz = dense_joint_moments(params, T)
    mu = np.concatenate([H @ mi for mi in means])
    Sigma = np.zeros((T * k, T * k))
    for s in range(T):
        for t in range(T):
            Sigma[s * k : (s + 1) * k, t * k : (t + 1) * k] = (
                H @ covz[s, t] @ H.T
            )
    Sigma[np.diag_indices(T * k)] += np.exp(float(params["log_r"]))
    yf = np.asarray(y, np.float64).reshape(-1)
    diff = yf - mu
    sign, logdet = np.linalg.slogdet(Sigma)
    assert sign > 0
    return float(
        -0.5 * diff @ np.linalg.solve(Sigma, diff)
        - 0.5 * logdet
        - 0.5 * T * k * np.log(2 * np.pi)
    )


class TestKalmanSequential:
    def test_matches_dense_joint(self):
        y, params = generate_lgssm_data(T=6)
        lp = float(kalman_logp_seq(params, y))
        ref = dense_joint_logp(params, y)
        np.testing.assert_allclose(lp, ref, rtol=1e-4)


class TestKalmanParallel:
    def test_matches_sequential(self):
        y, params = generate_lgssm_data(T=64)
        lp_seq = float(kalman_logp_seq(params, y))
        lp_par = float(kalman_logp_parallel(params, y))
        np.testing.assert_allclose(lp_par, lp_seq, rtol=1e-4)

    def test_gradients_match(self):
        y, params = generate_lgssm_data(T=32)
        g_seq = jax.jit(jax.grad(lambda p: kalman_logp_seq(p, y)))(params)
        g_par = jax.jit(jax.grad(lambda p: kalman_logp_parallel(p, y)))(
            params
        )
        for key in params:
            np.testing.assert_allclose(
                np.asarray(g_par[key]),
                np.asarray(g_seq[key]),
                rtol=1e-3,
                atol=1e-4,
                err_msg=key,
            )


class TestSmoother:
    def test_parallel_matches_sequential(self):
        y, params = generate_lgssm_data(T=64)
        sm_s, sP_s = kalman_smoother_seq(params, y)
        sm_p, sP_p = kalman_smoother_parallel(params, y)
        np.testing.assert_allclose(
            np.asarray(sm_p), np.asarray(sm_s), rtol=1e-3, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(sP_p), np.asarray(sP_s), rtol=1e-3, atol=1e-4
        )

    def test_matches_dense_conditional(self):
        """Smoothed marginals vs the exact joint-Gaussian conditional
        p(z_t | y_{1:T}) built densely (tiny T)."""
        y, params = generate_lgssm_data(T=5)
        T = 5
        H = np.asarray(params["H"], np.float64)
        d, k = np.asarray(params["F"]).shape[0], H.shape[0]
        means, covz = dense_joint_moments(params, T)
        mu_z = np.concatenate(means)
        bigH = np.kron(np.eye(T), H)
        Sz = covz.transpose(0, 2, 1, 3).reshape(T * d, T * d)
        Syy = bigH @ Sz @ bigH.T + np.exp(float(params["log_r"])) * np.eye(T * k)
        Szy = Sz @ bigH.T
        yf = np.asarray(y, np.float64).reshape(-1)
        post_mean = mu_z + Szy @ np.linalg.solve(Syy, yf - bigH @ mu_z)
        post_cov = Sz - Szy @ np.linalg.solve(Syy, Szy.T)
        sm, sP = kalman_smoother_parallel(params, y)
        for t in range(T):
            np.testing.assert_allclose(
                np.asarray(sm[t]),
                post_mean[t * d : (t + 1) * d],
                rtol=1e-3,
                atol=1e-4,
            )
            np.testing.assert_allclose(
                np.asarray(sP[t]),
                post_cov[t * d : (t + 1) * d, t * d : (t + 1) * d],
                rtol=1e-3,
                atol=1e-4,
            )


class TestSimulationSmoother:
    def test_moments_match_smoother(self):
        """Posterior draws must reproduce the smoothed mean and the
        marginal smoothed variances (exactness of Durbin-Koopman for
        linear-Gaussian models), up to Monte Carlo error."""
        from pytensor_federated_tpu.models.statespace import sample_latents

        y, params = generate_lgssm_data(T=24)
        sm, sP = kalman_smoother_parallel(params, y)
        draws = jax.jit(
            lambda k: sample_latents(params, y, k, num_draws=4000)
        )(jax.random.PRNGKey(0))
        assert draws.shape == (4000, 24, 2)
        emp_mean = jnp.mean(draws, axis=0)
        emp_var = jnp.var(draws, axis=0)
        np.testing.assert_allclose(
            np.asarray(emp_mean), np.asarray(sm), atol=0.05
        )
        np.testing.assert_allclose(
            np.asarray(emp_var),
            np.asarray(jax.vmap(jnp.diag)(sP)),
            rtol=0.15,
            atol=0.01,
        )


class TestSeqSharded:
    @pytest.fixture(scope="class")
    def seq_mesh(self, devices8):
        return make_mesh({"seq": 4}, devices=devices8[:4])

    def test_matches_sequential(self, seq_mesh):
        y, params = generate_lgssm_data(T=64)
        model = SeqShardedLGSSM(y, mesh=seq_mesh, axis="seq")
        lp = float(model.logp(params))
        ref = float(kalman_logp_seq(params, y))
        np.testing.assert_allclose(lp, ref, rtol=1e-4)

    def test_logp_and_grad(self, seq_mesh):
        y, params = generate_lgssm_data(T=64)
        model = SeqShardedLGSSM(y, mesh=seq_mesh, axis="seq")
        v, g = model.logp_and_grad(params)
        ref_g = jax.grad(lambda p: kalman_logp_seq(p, y))(params)
        np.testing.assert_allclose(
            float(v), float(kalman_logp_seq(params, y)), rtol=1e-4
        )
        for key in params:
            np.testing.assert_allclose(
                np.asarray(g[key]),
                np.asarray(ref_g[key]),
                rtol=1e-3,
                atol=1e-4,
                err_msg=key,
            )

    def test_distributed_smoother_matches(self, seq_mesh):
        """Distributed reverse segment-summary scan == single-device
        parallel smoother, with and without a mask."""
        y, params = generate_lgssm_data(T=64)
        rng = np.random.default_rng(17)
        mask = (rng.uniform(size=64) > 0.25).astype(np.float32)
        # Deterministically hit the special-cased rows: global first
        # and last observations, plus one full device segment (rows
        # 16..31 on the 4-device mesh) so segment-boundary composition
        # under total missingness is exercised.
        mask[0] = 0.0
        mask[-1] = 0.0
        mask[16:32] = 0.0
        for m in (None, mask):
            model = SeqShardedLGSSM(y, mesh=seq_mesh, axis="seq", mask=m)
            sm_d, sP_d = model.smoothed_moments(params)
            sm_ref, sP_ref = kalman_smoother_parallel(params, y, m)
            np.testing.assert_allclose(
                np.asarray(sm_d), np.asarray(sm_ref), rtol=1e-3, atol=1e-4
            )
            np.testing.assert_allclose(
                np.asarray(sP_d), np.asarray(sP_ref), rtol=1e-3, atol=1e-4
            )

    def test_distributed_sample_latents_moments(self, seq_mesh):
        """Distributed simulation-smoother draws reproduce the
        (distributed) smoothed mean and marginal variances."""
        y, params = generate_lgssm_data(T=16)
        model = SeqShardedLGSSM(y, mesh=seq_mesh, axis="seq")
        sm, sP = model.smoothed_moments(params)
        draws = model.sample_latents(
            params, jax.random.PRNGKey(8), num_draws=3000
        )
        assert draws.shape == (3000, 16, 2)
        np.testing.assert_allclose(
            np.asarray(jnp.mean(draws, axis=0)),
            np.asarray(sm),
            atol=0.06,
        )
        np.testing.assert_allclose(
            np.asarray(jnp.var(draws, axis=0)),
            np.asarray(jax.vmap(jnp.diag)(sP)),
            rtol=0.2,
            atol=0.01,
        )

    def test_distributed_forecast_matches(self, seq_mesh):
        from pytensor_federated_tpu.models.statespace import kalman_forecast

        y, params = generate_lgssm_data(T=32)
        rng = np.random.default_rng(23)
        mask = (rng.uniform(size=32) > 0.3).astype(np.float32)
        for m in (None, mask):
            model = SeqShardedLGSSM(y, mesh=seq_mesh, axis="seq", mask=m)
            my_d, Py_d = model.forecast(params, 4)
            my_r, Py_r = kalman_forecast(params, y, 4, mask=m)
            np.testing.assert_allclose(
                np.asarray(my_d), np.asarray(my_r), rtol=1e-4, atol=1e-6
            )
            np.testing.assert_allclose(
                np.asarray(Py_d), np.asarray(Py_r), rtol=1e-4, atol=1e-6
            )

    def test_indivisible_raises(self, seq_mesh):
        y, _ = generate_lgssm_data(T=30)
        with pytest.raises(ValueError, match="not divisible"):
            SeqShardedLGSSM(y, mesh=seq_mesh, axis="seq")

    def test_bad_axis_raises(self, seq_mesh):
        y, _ = generate_lgssm_data(T=64)
        with pytest.raises(ValueError, match="no axis"):
            SeqShardedLGSSM(y, mesh=seq_mesh, axis="nope")


class TestMissingData:
    def test_masked_logp_matches_dense_subset(self):
        """Masked marginal == exact joint-Gaussian marginal over only
        the observed rows (the defining property of missing-data
        filtering)."""
        y, params = generate_lgssm_data(T=8)
        mask = np.array([1, 1, 0, 1, 0, 0, 1, 1], np.float32)
        H = np.asarray(params["H"], np.float64)
        k = H.shape[0]
        means, covz = dense_joint_moments(params, 8)
        mu = np.concatenate([H @ mi for mi in means])
        Sigma = np.zeros((8 * k, 8 * k))
        for s in range(8):
            for t in range(8):
                Sigma[s * k : (s + 1) * k, t * k : (t + 1) * k] = (
                    H @ covz[s, t] @ H.T
                )
        Sigma[np.diag_indices(8 * k)] += np.exp(float(params["log_r"]))
        obs = np.where(np.repeat(mask, k) > 0)[0]
        So = Sigma[np.ix_(obs, obs)]
        yo = np.asarray(y, np.float64).reshape(-1)[obs] - mu[obs]
        sign, logdet = np.linalg.slogdet(So)
        ref = float(
            -0.5 * yo @ np.linalg.solve(So, yo)
            - 0.5 * logdet
            - 0.5 * len(obs) * np.log(2 * np.pi)
        )
        lp_seq = float(kalman_logp_seq(params, y, mask))
        lp_par = float(kalman_logp_parallel(params, y, mask))
        np.testing.assert_allclose(lp_seq, ref, rtol=1e-4)
        np.testing.assert_allclose(lp_par, ref, rtol=1e-4)

    def test_all_observed_equals_unmasked(self):
        y, params = generate_lgssm_data(T=16)
        lp = float(kalman_logp_parallel(params, y))
        lp_m = float(
            kalman_logp_parallel(params, y, jnp.ones(16))
        )
        np.testing.assert_allclose(lp_m, lp, rtol=1e-6)

    def test_sharded_masked_matches(self, devices8):
        mesh = make_mesh({"seq": 4}, devices=devices8[:4])
        y, params = generate_lgssm_data(T=32)
        rng = np.random.default_rng(5)
        mask = (rng.uniform(size=32) > 0.3).astype(np.float32)
        mask[0] = 0.0  # masked global t=1 exercises the prior element
        model = SeqShardedLGSSM(y, mesh=mesh, axis="seq", mask=mask)
        lp = float(model.logp(params))
        ref = float(kalman_logp_seq(params, y, mask))
        np.testing.assert_allclose(lp, ref, rtol=1e-4)
        v, g = model.logp_and_grad(params)
        ref_g = jax.grad(lambda p: kalman_logp_seq(p, y, mask))(params)
        for key in params:
            np.testing.assert_allclose(
                np.asarray(g[key]),
                np.asarray(ref_g[key]),
                rtol=1e-3,
                atol=1e-4,
                err_msg=key,
            )

    def test_nan_encoded_missing(self):
        """Masked rows may hold NaN (pandas convention) without
        poisoning the logp or its gradient."""
        y, params = generate_lgssm_data(T=16)
        mask = np.ones(16, np.float32)
        mask[[3, 7, 8]] = 0.0
        y_nan = np.asarray(y).copy()
        y_nan[[3, 7, 8]] = np.nan
        ref = float(kalman_logp_seq(params, y, mask))
        for fn in (kalman_logp_seq, kalman_logp_parallel):
            lp = float(fn(params, jnp.asarray(y_nan), mask))
            np.testing.assert_allclose(lp, ref, rtol=1e-5)
            g = jax.grad(lambda p: fn(p, jnp.asarray(y_nan), mask))(params)
            assert all(
                bool(jnp.all(jnp.isfinite(leaf)))
                for leaf in jax.tree_util.tree_leaves(g)
            )

    def test_masked_smoother_matches_dense_conditional(self):
        """Smoothed marginals under a mask == exact conditional
        E[z_t | observed y] from the dense joint."""
        y, params = generate_lgssm_data(T=6)
        T = 6
        mask = np.array([1, 0, 1, 1, 0, 1], np.float32)
        H = np.asarray(params["H"], np.float64)
        d, k = np.asarray(params["F"]).shape[0], H.shape[0]
        means, covz = dense_joint_moments(params, T)
        mu_z = np.concatenate(means)
        bigH = np.kron(np.eye(T), H)
        Sz = covz.transpose(0, 2, 1, 3).reshape(T * d, T * d)
        Syy = bigH @ Sz @ bigH.T + np.exp(
            float(params["log_r"])
        ) * np.eye(T * k)
        Szy = Sz @ bigH.T
        obs = np.where(np.repeat(mask, k) > 0)[0]
        yf = np.asarray(y, np.float64).reshape(-1)
        resid = (yf - bigH @ mu_z)[obs]
        So = Syy[np.ix_(obs, obs)]
        post_mean = mu_z + Szy[:, obs] @ np.linalg.solve(So, resid)
        post_cov = Sz - Szy[:, obs] @ np.linalg.solve(
            So, Szy[:, obs].T
        )
        sm_s, sP_s = kalman_smoother_seq(params, y, mask)
        sm_p, sP_p = kalman_smoother_parallel(params, y, mask)
        for sm, sP in ((sm_s, sP_s), (sm_p, sP_p)):
            for t in range(T):
                np.testing.assert_allclose(
                    np.asarray(sm[t]),
                    post_mean[t * d : (t + 1) * d],
                    rtol=1e-3,
                    atol=1e-4,
                )
                np.testing.assert_allclose(
                    np.asarray(sP[t]),
                    post_cov[t * d : (t + 1) * d, t * d : (t + 1) * d],
                    rtol=1e-3,
                    atol=1e-4,
                )

    def test_masked_sample_latents_moments(self):
        from pytensor_federated_tpu.models.statespace import sample_latents

        y, params = generate_lgssm_data(T=12)
        mask = np.ones(12, np.float32)
        mask[[2, 5, 9]] = 0.0
        sm, sP = kalman_smoother_parallel(params, y, mask)
        draws = jax.jit(
            lambda k: sample_latents(params, y, k, num_draws=4000, mask=mask)
        )(jax.random.PRNGKey(1))
        np.testing.assert_allclose(
            np.asarray(jnp.mean(draws, axis=0)), np.asarray(sm), atol=0.08
        )
        np.testing.assert_allclose(
            np.asarray(jnp.var(draws, axis=0)),
            np.asarray(jax.vmap(jnp.diag)(sP)),
            rtol=0.15,
            atol=0.02,
        )

    def test_ragged_panel(self, devices8):
        """Padded + masked panel == sum of per-series logps at their
        true lengths."""
        from pytensor_federated_tpu.models.statespace import (
            FederatedLGSSMPanel,
        )

        mesh = make_mesh({"shards": 4}, devices=devices8[:4])
        lengths = [32, 24, 16, 8]
        T = 32
        series, masks = [], []
        for i, L in enumerate(lengths):
            y_i, params = generate_lgssm_data(T=L, seed=300 + i)
            pad = np.zeros((T, 1), np.float32)
            pad[:L] = np.asarray(y_i)
            series.append(pad)
            m = np.zeros(T, np.float32)
            m[:L] = 1.0
            masks.append(m)
        ys = jnp.asarray(np.stack(series))
        panel = FederatedLGSSMPanel(
            ys, mesh=mesh, masks=jnp.asarray(np.stack(masks))
        )
        lp = float(panel.logp(params))
        ref = 0.0
        for i, L in enumerate(lengths):
            ref += float(kalman_logp_seq(params, ys[i, :L]))
        np.testing.assert_allclose(lp, ref, rtol=1e-4)


class TestEKF:
    def test_linear_model_matches_kalman_exactly(self):
        """With affine f/h the EKF's linearization is exact, so its
        logp must equal the linear Kalman filter's."""
        from pytensor_federated_tpu.models.statespace import ekf_logp

        y, params = generate_lgssm_data(T=32)
        d = np.asarray(params["F"]).shape[0]
        k = np.asarray(params["H"]).shape[0]
        Q = jnp.exp(params["log_q"]) * jnp.eye(d)
        R = jnp.exp(params["log_r"]) * jnp.eye(k)

        def f(p, z):
            return p["F"] @ z

        def h(p, z):
            return p["H"] @ z

        lp = float(
            ekf_logp(
                f, h, params, y, Q=Q, R=R,
                m0=params["m0"], P0=jnp.eye(d),
            )
        )
        ref = float(kalman_logp_seq(params, y))
        np.testing.assert_allclose(lp, ref, rtol=1e-4)

    def test_nonlinear_map_recovers_param(self):
        """Noisy stochastic growth model: MAP over the growth rate via
        grad-through-the-EKF lands near the truth."""
        from pytensor_federated_tpu.models.statespace import ekf_logp

        rng = np.random.default_rng(11)
        r_true = 0.8
        T = 200
        z = 0.5
        ys = []
        for _ in range(T):
            z = r_true * z * (1.0 - z) + 0.3 + 0.02 * rng.normal()
            ys.append(z + 0.05 * rng.normal())
        y = jnp.asarray(np.array(ys, np.float32))[:, None]

        def f(p, z):
            return p["r"] * z * (1.0 - z) + 0.3

        def h(p, z):
            return z

        Q = 4e-4 * jnp.eye(1)
        R = 25e-4 * jnp.eye(1)

        def logp(p):
            return ekf_logp(
                f, h, p, y, Q=Q, R=R,
                m0=jnp.asarray([0.5]), P0=jnp.eye(1),
            )

        # Gradient ascent from a perturbed start.
        p = {"r": jnp.asarray(0.5)}
        g_fn = jax.jit(jax.value_and_grad(logp))
        for _ in range(100):
            v, g = g_fn(p)
            p = {"r": p["r"] + 1e-4 * g["r"]}
        assert abs(float(p["r"]) - r_true) < 0.1, float(p["r"])

    def test_masked_matches_subset_consistency(self):
        """EKF with affine f/h and a mask == masked linear filter."""
        from pytensor_federated_tpu.models.statespace import ekf_logp

        y, params = generate_lgssm_data(T=16)
        mask = np.ones(16, np.float32)
        mask[[2, 9]] = 0.0
        d = np.asarray(params["F"]).shape[0]
        k = np.asarray(params["H"]).shape[0]
        lp = float(
            ekf_logp(
                lambda p, z: p["F"] @ z,
                lambda p, z: p["H"] @ z,
                params,
                y,
                Q=jnp.exp(params["log_q"]) * jnp.eye(d),
                R=jnp.exp(params["log_r"]) * jnp.eye(k),
                m0=params["m0"],
                P0=jnp.eye(d),
                mask=mask,
            )
        )
        ref = float(kalman_logp_seq(params, y, mask))
        np.testing.assert_allclose(lp, ref, rtol=1e-4)


class TestLag1Smoother:
    def test_matches_dense_cross_covariance(self):
        """Lag-one smoothed cross-covs vs the exact joint conditional."""
        from pytensor_federated_tpu.models.statespace import (
            kalman_smoother_with_lag1,
        )

        y, params = generate_lgssm_data(T=5)
        T = 5
        H = np.asarray(params["H"], np.float64)
        d, k = np.asarray(params["F"]).shape[0], H.shape[0]
        means, covz = dense_joint_moments(params, T)
        mu_z = np.concatenate(means)
        bigH = np.kron(np.eye(T), H)
        Sz = covz.transpose(0, 2, 1, 3).reshape(T * d, T * d)
        Syy = bigH @ Sz @ bigH.T + np.exp(
            float(params["log_r"])
        ) * np.eye(T * k)
        Szy = Sz @ bigH.T
        post_cov = Sz - Szy @ np.linalg.solve(Syy, Szy.T)
        _, _, lag1 = kalman_smoother_with_lag1(params, y)
        for t in range(T - 1):
            want = post_cov[
                (t + 1) * d : (t + 2) * d, t * d : (t + 1) * d
            ]
            np.testing.assert_allclose(
                np.asarray(lag1[t]), want, rtol=1e-3, atol=1e-4
            )


class TestEM:
    def test_monotone_and_recovers_scales(self):
        from pytensor_federated_tpu.models.statespace import lgssm_em

        y, true = generate_lgssm_data(T=512)
        init = dict(
            true,
            F=0.5 * jnp.eye(2),
            log_q=jnp.asarray(-3.0),
            log_r=jnp.asarray(0.5),
        )
        fitted, lls = lgssm_em(init, y, num_iters=30)
        lls = np.asarray(lls)
        # EM invariant: the marginal loglik is monotone non-decreasing.
        assert np.all(np.diff(lls) > -1e-2), np.diff(lls).min()
        # Substantial improvement over the perturbed start...
        assert lls[-1] > lls[0] + 10.0
        # ...and the noise scales land near the generating values.
        assert abs(float(fitted["log_q"]) - float(true["log_q"])) < 0.7
        assert abs(float(fitted["log_r"]) - float(true["log_r"])) < 0.7
        # No assertion against the generating F or the truth's
        # likelihood: F is only weakly identified from 1-D observations
        # of a 2-D latent (similarity transforms leave the likelihood
        # nearly flat), and EM famously crawls along that manifold —
        # finite-iteration proximity to the truth is not an EM
        # guarantee.  Monotonicity, the large improvement, and the
        # recovered noise scales above are.
        assert np.isfinite(np.asarray(fitted["F"])).all()

    def test_panel_duplicate_series_equals_single(self):
        """Pooled statistics over two copies of one series must give
        exactly the single-series update (numerators and denominators
        both double)."""
        from pytensor_federated_tpu.models.statespace import (
            lgssm_em,
            panel_em,
        )

        y, true = generate_lgssm_data(T=128)
        init = dict(true, log_q=jnp.asarray(-2.0), log_r=jnp.asarray(0.2))
        single, lls1 = lgssm_em(init, y, num_iters=5)
        panel, lls2 = panel_em(
            init, jnp.stack([y, y]), num_iters=5
        )
        for key in single:
            np.testing.assert_allclose(
                np.asarray(panel[key]),
                np.asarray(single[key]),
                rtol=1e-4,
                atol=1e-5,
                err_msg=key,
            )
        np.testing.assert_allclose(
            np.asarray(lls2), 2.0 * np.asarray(lls1), rtol=1e-5
        )

    @staticmethod
    def _simulate_under(params, rng, T):
        """Simulate one series under the GIVEN shared parameters (the
        panel contract generate_lgssm_data cannot honor — it draws a
        fresh H per call)."""
        F = np.asarray(params["F"], np.float64)
        H = np.asarray(params["H"], np.float64)
        d, k = F.shape[0], H.shape[0]
        q = np.exp(float(params["log_q"]))
        r = np.exp(float(params["log_r"]))
        z = rng.normal(size=d)
        ys = []
        for _ in range(T):
            z = F @ z + np.sqrt(q) * rng.normal(size=d)
            ys.append(H @ z + np.sqrt(r) * rng.normal(size=k))
        return np.stack(ys).astype(np.float32)

    def test_panel_em_monotone_ragged(self):
        from pytensor_federated_tpu.models.statespace import panel_em

        _, params = generate_lgssm_data(T=8, seed=404)
        rng = np.random.default_rng(13)
        series, masks = [], []
        for L in [96, 64, 32]:
            y_i = self._simulate_under(params, rng, L)
            pad = np.zeros((96, 1), np.float32)
            pad[:L] = y_i
            m = np.zeros(96, np.float32)
            m[:L] = 1.0
            series.append(pad)
            masks.append(m)
        init = dict(params, log_q=jnp.asarray(-2.5), log_r=jnp.asarray(0.4))
        fitted, lls = panel_em(
            init,
            jnp.asarray(np.stack(series)),
            masks=jnp.asarray(np.stack(masks)),
            num_iters=12,
        )
        lls = np.asarray(lls)
        assert np.all(np.diff(lls) > -1e-2), np.diff(lls).min()
        assert lls[-1] > lls[0]
        # Shared-parameter data: the pooled noise scales must land near
        # the generating values (log 0.1 / log 0.5).
        assert abs(float(fitted["log_q"]) - float(params["log_q"])) < 0.6
        assert abs(float(fitted["log_r"]) - float(params["log_r"])) < 0.6

    def test_large_magnitude_data_stable_in_float32(self):
        """Unstandardized data (|y| ~ 100, noise ~ 0.1): the residual-
        form emission update must keep r positive — the raw-moment form
        yy - 2tr(H Syz') + tr(H Szz H') cancels catastrophically here,
        clamps R to ~0, and destabilizes every later iteration."""
        from pytensor_federated_tpu.models.statespace import lgssm_em

        _, params = generate_lgssm_data(T=8, seed=77)
        big = dict(
            params,
            H=100.0 * params["H"],
            log_r=jnp.asarray(np.log(0.01), jnp.float32),
        )
        rng = np.random.default_rng(21)
        y = self._simulate_under(big, rng, 512)
        init = dict(big, log_r=jnp.asarray(np.log(0.05), jnp.float32))
        fitted, lls = lgssm_em(init, jnp.asarray(y), num_iters=8)
        lls = np.asarray(lls)
        assert np.isfinite(lls).all(), lls
        assert np.all(np.diff(lls) > -1e-1), np.diff(lls).min()
        # r stays at noise scale, never clamped toward zero.
        assert float(fitted["log_r"]) > np.log(1e-4), float(
            fitted["log_r"]
        )

    def test_fit_H_and_masked(self):
        from pytensor_federated_tpu.models.statespace import lgssm_em

        y, true = generate_lgssm_data(T=256)
        rng = np.random.default_rng(9)
        mask = (rng.uniform(size=256) > 0.2).astype(np.float32)
        init = dict(true, log_r=jnp.asarray(0.3))
        fitted, lls = lgssm_em(
            init, y, num_iters=15, mask=mask, fit_H=True
        )
        lls = np.asarray(lls)
        assert np.all(np.diff(lls) > -1e-2), np.diff(lls).min()
        assert np.isfinite(np.asarray(fitted["H"])).all()


class TestForecast:
    def test_matches_dense_joint_conditional(self):
        """Forecast moments == conditional moments of future y rows in
        the dense joint Gaussian built over T+h steps."""
        from pytensor_federated_tpu.models.statespace import kalman_forecast

        T, h = 6, 3
        y_full, params = generate_lgssm_data(T=T + h)
        y = y_full[:T]
        H = np.asarray(params["H"], np.float64)
        d, k = np.asarray(params["F"]).shape[0], H.shape[0]
        means, covz = dense_joint_moments(params, T + h)
        mu_z = np.concatenate(means)
        bigH = np.kron(np.eye(T + h), H)
        Sz = covz.transpose(0, 2, 1, 3).reshape((T + h) * d, (T + h) * d)
        Syy = bigH @ Sz @ bigH.T + np.exp(
            float(params["log_r"])
        ) * np.eye((T + h) * k)
        mu_y = bigH @ mu_z
        past = np.arange(T * k)
        fut = np.arange(T * k, (T + h) * k)
        Spp = Syy[np.ix_(past, past)]
        Sfp = Syy[np.ix_(fut, past)]
        resid = np.asarray(y, np.float64).reshape(-1) - mu_y[past]
        cond_mean = mu_y[fut] + Sfp @ np.linalg.solve(Spp, resid)
        cond_cov = Syy[np.ix_(fut, fut)] - Sfp @ np.linalg.solve(
            Spp, Sfp.T
        )
        my, Py = kalman_forecast(params, y, h)
        assert my.shape == (h, k) and Py.shape == (h, k, k)
        for i in range(h):
            np.testing.assert_allclose(
                np.asarray(my[i]),
                cond_mean[i * k : (i + 1) * k],
                rtol=1e-3,
                atol=1e-4,
            )
            np.testing.assert_allclose(
                np.asarray(Py[i]),
                cond_cov[i * k : (i + 1) * k, i * k : (i + 1) * k],
                rtol=1e-3,
                atol=1e-4,
            )


    def test_masked_tail_equals_truncated_series(self):
        """Masking the last rows must equal forecasting further ahead
        from the truncated series — masked steps advance time purely
        predictively."""
        from pytensor_federated_tpu.models.statespace import kalman_forecast

        T, h = 12, 3
        y, params = generate_lgssm_data(T=T)
        mask = np.ones(T, np.float32)
        mask[-2:] = 0.0
        my_masked, Py_masked = kalman_forecast(params, y, h, mask=mask)
        my_trunc, Py_trunc = kalman_forecast(params, y[: T - 2], h + 2)
        np.testing.assert_allclose(
            np.asarray(my_masked), np.asarray(my_trunc[2:]), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(Py_masked),
            np.asarray(Py_trunc[2:]),
            rtol=1e-4,
            atol=1e-6,
        )


class TestFederatedPanel:
    def test_matches_sum_of_individual_logps(self, devices8):
        from pytensor_federated_tpu.models.statespace import (
            FederatedLGSSMPanel,
        )

        mesh = make_mesh({"shards": 4}, devices=devices8[:4])
        series = []
        for i in range(8):  # 2 local series per device
            y_i, params = generate_lgssm_data(T=32, seed=100 + i)
            series.append(np.asarray(y_i))
        ys = jnp.asarray(np.stack(series))
        panel = FederatedLGSSMPanel(ys, mesh=mesh)
        lp = float(panel.logp(params))

        def ref_total(p):
            return sum(kalman_logp_seq(p, ys[i]) for i in range(8))

        ref_v, ref_g = jax.jit(jax.value_and_grad(ref_total))(params)
        ref = float(ref_v)
        np.testing.assert_allclose(lp, ref, rtol=1e-4)

        v, g = panel.logp_and_grad(params)
        np.testing.assert_allclose(float(v), ref, rtol=1e-4)
        for key in params:
            np.testing.assert_allclose(
                np.asarray(g[key]),
                np.asarray(ref_g[key]),
                rtol=1e-3,
                atol=1e-3,
                err_msg=key,
            )


class TestSamplerIntegration:
    def test_nuts_recovers_noise_scales(self):
        """End-to-end: NUTS over (log_q, log_r) with the Kalman filter
        as the likelihood (posterior-accuracy pattern from the
        reference, test_wrapper_ops.py:105-117).  Uses the sequential
        filter — it compiles far faster than the associative-scan path
        and their equivalence (values and grads) is proven above."""
        from pytensor_federated_tpu.samplers import sample

        y, true = generate_lgssm_data(T=128)

        def logp(free):
            params = dict(true, log_q=free["log_q"], log_r=free["log_r"])
            # Weak N(0, 2) prior on both log-scales.
            prior = -(free["log_q"] ** 2 + free["log_r"] ** 2) / 8.0
            return prior + kalman_logp_seq(params, y)

        res = sample(
            logp,
            {"log_q": jnp.asarray(0.0), "log_r": jnp.asarray(0.0)},
            key=jax.random.PRNGKey(3),
            num_warmup=150,
            num_samples=150,
            num_chains=2,
        )
        post_q = float(jnp.mean(res.samples["log_q"]))
        post_r = float(jnp.mean(res.samples["log_r"]))
        # True values: log 0.1 ~ -2.30, log 0.5 ~ -0.69.
        assert abs(post_q - float(true["log_q"])) < 0.6, post_q
        assert abs(post_r - float(true["log_r"])) < 0.6, post_r
        rhat = res.summary()["rhat"]
        assert float(rhat["log_q"]) < 1.1
        assert float(rhat["log_r"]) < 1.1
