"""Minibatch shard subsampling + SGLD (parallel/sharded.py, samplers/sgld.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.parallel import FederatedLogp, make_mesh
from pytensor_federated_tpu.samplers.sgld import (
    polynomial_decay,
    psgld_sample,
    sghmc_sample,
    sgld_sample,
)


def _quadratic_setup(n_shards=16):
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.normal(size=(n_shards, 8)).astype(np.float32))

    def per_shard(params, shard):
        return -0.5 * jnp.sum((shard - params["mu"]) ** 2)

    return per_shard, data


class TestMinibatch:
    def test_unbiased_logp_single_device(self):
        per_shard, data = _quadratic_setup()
        fed = FederatedLogp(per_shard, data)
        params = {"mu": jnp.asarray(0.3)}
        full = float(fed.logp(params))
        keys = jax.random.split(jax.random.PRNGKey(1), 600)
        ests = jax.vmap(
            lambda k: fed.logp_minibatch(params, k, num_shards=4)
        )(keys)
        se = float(jnp.std(ests)) / np.sqrt(len(keys))
        assert abs(float(jnp.mean(ests)) - full) < 5 * se + 1e-3
        assert float(jnp.std(ests)) > 0.0  # genuinely stochastic

    def test_unbiased_grad_and_mesh_path(self, devices8):
        per_shard, data = _quadratic_setup()
        mesh = make_mesh({"shards": 4}, devices=devices8[:4])
        fed = FederatedLogp(per_shard, data, mesh=mesh)
        params = {"mu": jnp.asarray(-0.7)}
        _, g_full = fed.logp_and_grad(params)
        keys = jax.random.split(jax.random.PRNGKey(2), 400)
        ests = jax.vmap(
            lambda k: fed.logp_and_grad_minibatch(params, k, num_shards=8)[
                1
            ]["mu"]
        )(keys)
        se = float(jnp.std(ests)) / np.sqrt(len(keys))
        assert abs(float(jnp.mean(ests)) - float(g_full["mu"])) < 5 * se + 1e-3

    def test_full_subset_equals_exact(self):
        per_shard, data = _quadratic_setup()
        fed = FederatedLogp(per_shard, data)
        params = {"mu": jnp.asarray(1.1)}
        est = float(
            fed.logp_minibatch(
                params, jax.random.PRNGKey(3), num_shards=16
            )
        )
        np.testing.assert_allclose(est, float(fed.logp(params)), rtol=1e-5)

    def test_validation(self, devices8):
        per_shard, data = _quadratic_setup()
        fed = FederatedLogp(per_shard, data)
        with pytest.raises(ValueError, match="num_shards"):
            fed.logp_minibatch(
                {"mu": jnp.asarray(0.0)}, jax.random.PRNGKey(0), 0
            )
        mesh = make_mesh({"shards": 4}, devices=devices8[:4])
        fed_m = FederatedLogp(per_shard, data, mesh=mesh)
        with pytest.raises(ValueError, match="not divisible"):
            fed_m.logp_minibatch(
                {"mu": jnp.asarray(0.0)}, jax.random.PRNGKey(0), 6
            )


class TestSGLD:
    def test_gaussian_target_full_batch(self):
        """Full-batch Langevin on a known Gaussian posterior: small
        constant step, moments must match."""

        def oracle(params, _key):
            return jax.value_and_grad(
                lambda p: -0.5 * jnp.sum((p["x"] - 2.0) ** 2 / 0.25)
            )(params)

        res = sgld_sample(
            oracle,
            {"x": jnp.zeros(2)},
            jax.random.PRNGKey(0),
            num_samples=4000,
            num_burnin=1000,
            step_size=0.01,
            thin=2,
        )
        xs = res.samples["x"]
        np.testing.assert_allclose(
            np.asarray(jnp.mean(xs, axis=0)), [2.0, 2.0], atol=0.1
        )
        # Langevin with eps=0.01 inflates variance by ~eps/4 only.
        np.testing.assert_allclose(
            np.asarray(jnp.var(xs, axis=0)), [0.25, 0.25], rtol=0.25
        )

    def test_sghmc_gaussian_target(self):
        def oracle(params, _key):
            return jax.value_and_grad(
                lambda p: -0.5 * jnp.sum((p["x"] + 1.0) ** 2 / 0.5)
            )(params)

        # Near-critical damping (C ~ sqrt(curvature)) mixes fastest:
        # more friction pushes into the slow overdamped regime, less
        # into underdamped oscillation.
        res = sghmc_sample(
            oracle,
            {"x": jnp.zeros(2)},
            jax.random.PRNGKey(5),
            num_samples=3000,
            num_burnin=500,
            step_size=0.05,
            friction=2.0,
            thin=3,
        )
        xs = res.samples["x"]
        np.testing.assert_allclose(
            np.asarray(jnp.mean(xs, axis=0)), [-1.0, -1.0], atol=0.1
        )
        np.testing.assert_allclose(
            np.asarray(jnp.var(xs, axis=0)), [0.5, 0.5], rtol=0.25
        )

    def test_psgld_anisotropic_target(self):
        """Badly-scaled Gaussian (sds 30x apart): the RMSProp
        preconditioner equalizes the per-coordinate dynamics, so one
        step size samples both coordinates accurately.  (Preconditioned
        relaxation time is ~sigma/eps steps, so the chain length fixes
        the widest coordinate's ESS at ~100.)"""
        scales = jnp.asarray([3.0, 0.1])

        def oracle(params, _key):
            return jax.value_and_grad(
                lambda p: -0.5 * jnp.sum((p["x"] / scales) ** 2)
            )(params)

        # beta must put the EMA's timescale well past the position
        # relaxation (~sigma/eps steps): a preconditioner that tracks
        # the current gradient biases the stationary tails (it is the
        # dropped Gamma-correction regime of the paper).
        res = psgld_sample(
            oracle,
            # 1 sd off the mode: the warm-started EMA needs a nonzero
            # init gradient for scale information (see docstring).
            {"x": jnp.asarray([3.0, 0.1])},
            jax.random.PRNGKey(6),
            num_samples=4000,
            num_burnin=2000,
            step_size=0.02,
            beta=0.999,
            thin=3,
        )
        xs = res.samples["x"]
        sd = np.asarray(jnp.std(xs, axis=0))
        # ~100 ESS at the wide coordinate leaves the realized sd
        # seed-and-XLA-version dependent; 45% covers the spread seen
        # across containers without letting a broken preconditioner
        # (30x scale error) through.
        np.testing.assert_allclose(sd, np.asarray(scales), rtol=0.45)
        # Mean within 0.4 posterior-sd per coordinate (~4 standard
        # errors at the widest coordinate's ESS of ~100).
        for i in range(2):
            assert abs(float(jnp.mean(xs[:, i]))) < 0.4 * sd[i], (
                i,
                float(jnp.mean(xs[:, i])),
            )

    def test_federated_minibatch_sgld(self):
        """Shard-subsampled SGLD on the federated quadratic: posterior
        concentrates at the data mean."""
        per_shard, data = _quadratic_setup()
        fed = FederatedLogp(per_shard, data)
        target_mu = float(jnp.mean(data))

        res = sgld_sample(
            lambda p, k: fed.logp_and_grad_minibatch(p, k, num_shards=4),
            {"mu": jnp.asarray(0.0)},
            jax.random.PRNGKey(4),
            num_samples=2000,
            num_burnin=1000,
            step_size=polynomial_decay(a=2e-3, gamma=0.55),
        )
        post_mean = float(jnp.mean(res.samples["mu"]))
        # Posterior sd of mu is 1/sqrt(n_obs) = 1/sqrt(128) ~ 0.088.
        assert abs(post_mean - target_mu) < 0.05, (post_mean, target_mu)
        assert np.isfinite(np.asarray(res.logps)).all()
