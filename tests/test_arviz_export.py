"""arviz-layout export: dict path always, real InferenceData if arviz."""

import jax
import numpy as np
import pytest

from pytensor_federated_tpu.models.logistic import (
    FederatedLogisticRegression,
    generate_logistic_data,
)
from pytensor_federated_tpu.samplers import to_dataset_dict


@pytest.fixture(scope="module")
def fitted():
    data, _ = generate_logistic_data(n_shards=4, n_obs=32, n_features=3)
    m = FederatedLogisticRegression(data)
    res = m.sample(
        key=jax.random.PRNGKey(0),
        num_warmup=100,
        num_samples=80,
        num_chains=2,
    )
    return m, res, data


def test_dataset_dict_layout(fitted):
    m, res, data = fitted
    groups = to_dataset_dict(res)
    post = groups["posterior"]
    assert set(post) == {"w", "b"}
    assert post["w"].shape == (2, 80, 3)
    stats = groups["sample_stats"]
    assert "diverging" in stats and "energy" in stats
    assert "tree_depth" in stats  # renamed from 'depth'
    assert stats["diverging"].shape == (2, 80)


def test_log_likelihood_group(fitted):
    m, res, data = fitted
    mask = data.tree()[1]

    def pointwise(params):
        (X, y), mk = data.tree()
        import jax.numpy as jnp

        logits = jnp.einsum("snd,d->sn", X, params["w"]) + params["b"]
        return (y * logits - jnp.logaddexp(0.0, logits)) * mk

    groups = to_dataset_dict(res, pointwise_fn=pointwise, mask=mask)
    ll = groups["log_likelihood"]["obs"]
    n_real = int(np.asarray(mask).sum())
    assert ll.shape == (2, 80, n_real)
    assert np.all(np.isfinite(ll))
    # log-likelihoods of Bernoulli outcomes are <= 0
    assert np.all(ll <= 0.0)


def test_nested_param_trees_flatten():
    from pytensor_federated_tpu.samplers.arviz_export import _as_mapping

    m = _as_mapping({"a": 1, "nest": {"b": 2, "c": 3}})
    assert set(m) == {"a", "nest.b", "nest.c"}


def test_inference_data_when_arviz_present(fitted):
    az = pytest.importorskip("arviz")
    from pytensor_federated_tpu.samplers import to_inference_data

    m, res, data = fitted
    idata = to_inference_data(res)
    assert hasattr(idata, "posterior")
    assert float(az.summary(idata)["r_hat"].max()) < 1.2
