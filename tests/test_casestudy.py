"""docs/casestudy.md's code blocks actually run (same executor pattern
as tests/test_tutorial.py): the full-workflow narrative is continuously
verified, with sampling sizes shrunk for test wall time."""

import re
from pathlib import Path

DOC = Path(__file__).resolve().parent.parent / "docs" / "casestudy.md"


def _blocks():
    text = DOC.read_text()
    return re.findall(r"```python\n(.*?)```", text, flags=re.S)


def test_casestudy_blocks_execute():
    ns: dict = {}
    blocks = _blocks()
    assert len(blocks) >= 6
    shrinks = {
        "num_warmup=500": "num_warmup=150",
        "num_samples=500": "num_samples=150",
        "num_chains=4": "num_chains=2",
        "num_draws=200": "num_draws=50",
    }
    seen = set()
    for i, block in enumerate(blocks):
        for old, new in shrinks.items():
            if old in block:
                seen.add(old)
                block = block.replace(old, new)
        exec(compile(block, f"{DOC.name}:block{i}", "exec"), ns)
    # every shrink literal must have matched at least once — drift in
    # the doc's literals would silently run the full-size study
    assert seen == set(shrinks), f"unmatched shrinks: {set(shrinks) - seen}"
