"""Replica-pool routing: breakers, policies, probing, failover, hedging.

Unit coverage for `pytensor_federated_tpu.routing` plus the satellite
contracts ISSUE 4 names: concurrent GetLoad probing with npwire AND
npproto replies parsed under parallel probes, stale-load eviction, the
zero-item TCP probe frame reused as the TCP health check, and the
elastic-sampling pool-recovery tier.  The SIGKILL-mid-window e2e lives
in tests/test_pool_e2e.py (real process boundaries).
"""

import asyncio
import socket
import threading
import time

import numpy as np
import pytest

from pytensor_federated_tpu.routing import (
    CircuitBreaker,
    EwmaLatencyPolicy,
    NodePool,
    PooledArraysClient,
    PowerOfTwoChoicesPolicy,
    RoundRobinPolicy,
    get_policy,
)
from pytensor_federated_tpu.routing.pool import _tcp_probe
from pytensor_federated_tpu.telemetry import flightrec


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _dead_port():
    """A port that refuses connections (bound then released)."""
    return _free_port()


def _quad(x):
    x = np.asarray(x)
    return [
        np.asarray(-np.sum((x - 3.0) ** 2)),
        (-2.0 * (x - 3.0)).astype(x.dtype),
    ]


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_trips_after_threshold_and_half_open_recovers(self):
        b = CircuitBreaker(
            failure_threshold=3, backoff_s=0.05, jitter_frac=0.0
        )
        for _ in range(2):
            b.record_failure()
        assert b.state == "closed" and b.available()
        b.record_failure()
        assert b.state == "open" and not b.available()
        assert not b.acquire()
        time.sleep(0.06)
        # deadline passed: half-open with exactly ONE probe token
        assert b.state == "half_open"
        assert b.acquire()
        assert not b.acquire(), "second half-open claimant must lose"
        b.record_success()
        assert b.state == "closed"
        assert b.consecutive_failures == 0

    def test_failed_probe_doubles_backoff_with_cap(self):
        b = CircuitBreaker(
            failure_threshold=1,
            backoff_s=0.02,
            max_backoff_s=0.05,
            jitter_frac=0.0,
        )
        b.record_failure()  # trip: deadline armed with 0.02
        assert b.backoff_s == pytest.approx(0.02)
        time.sleep(0.025)
        assert b.acquire()
        b.record_failure()  # failed probe: escalate
        assert b.backoff_s == pytest.approx(0.04)
        time.sleep(0.05)
        assert b.acquire()
        b.record_failure()  # escalate again, capped
        assert b.backoff_s == pytest.approx(0.05)

    def test_jittered_deadline_stays_in_band(self):
        import random

        for seed in range(20):
            b = CircuitBreaker(
                failure_threshold=1,
                backoff_s=1.0,
                jitter_frac=0.2,
                clock=lambda: 0.0,
                rng=random.Random(seed),
            )
            b.record_failure()
            assert 0.8 <= b._open_until <= 1.2

    def test_success_resets_backoff_ladder(self):
        b = CircuitBreaker(
            failure_threshold=1, backoff_s=0.01, jitter_frac=0.0
        )
        b.record_failure()
        time.sleep(0.015)
        assert b.acquire()
        b.record_failure()  # escalated to 0.02
        time.sleep(0.03)
        assert b.acquire()
        b.record_success()
        assert b.backoff_s == pytest.approx(0.01), "ladder must reset"

    def test_transition_hook_fires(self):
        seen = []
        b = CircuitBreaker(
            failure_threshold=1,
            backoff_s=0.01,
            jitter_frac=0.0,
            on_transition=lambda old, new: seen.append((old, new)),
        )
        b.record_failure()
        time.sleep(0.015)
        b.acquire()
        b.record_success()
        assert ("closed", "open") in seen
        assert ("open", "half_open") in seen
        assert ("half_open", "closed") in seen


# ---------------------------------------------------------------------------
# Policies
# ---------------------------------------------------------------------------


class _FakeReplica:
    def __init__(self, name, depth=None, ewma=None):
        self.address = name
        self._depth = depth
        self.ewma_latency_s = ewma
        self.inflight = 0

    def queue_depth(self):
        return self._depth


class TestPolicies:
    def test_round_robin_cycles(self):
        rr = RoundRobinPolicy()
        cands = [_FakeReplica(n) for n in "abc"]
        picks = [rr.pick(cands, 1)[0].address for _ in range(6)]
        assert picks == ["a", "b", "c", "a", "b", "c"]

    def test_round_robin_k_distinct(self):
        rr = RoundRobinPolicy()
        cands = [_FakeReplica(n) for n in "abc"]
        assert [r.address for r in rr.pick(cands, 2)] == ["a", "b"]
        assert [r.address for r in rr.pick(cands, 5)] == ["b", "c", "a"]

    def test_ewma_ranks_unmeasured_first_then_fastest(self):
        ew = EwmaLatencyPolicy()
        cands = [
            _FakeReplica("slow", ewma=0.5),
            _FakeReplica("fast", ewma=0.1),
            _FakeReplica("new"),
        ]
        assert [r.address for r in ew.pick(cands, 3)] == [
            "new",
            "fast",
            "slow",
        ]

    def test_p2c_prefers_lower_advertised_depth(self):
        import random

        p2c = PowerOfTwoChoicesPolicy(rng=random.Random(0))
        busy = _FakeReplica("busy", depth=10)
        idle = _FakeReplica("idle", depth=0)
        picks = [p2c.pick([busy, idle], 1)[0].address for _ in range(25)]
        assert all(p == "idle" for p in picks)

    def test_p2c_falls_back_to_ewma_on_ties(self):
        import random

        p2c = PowerOfTwoChoicesPolicy(rng=random.Random(0))
        a = _FakeReplica("a", depth=2, ewma=0.5)
        b = _FakeReplica("b", depth=2, ewma=0.1)
        picks = [p2c.pick([a, b], 1)[0].address for _ in range(25)]
        assert all(p == "b" for p in picks)

    def test_get_policy_validates(self):
        assert isinstance(get_policy("p2c"), PowerOfTwoChoicesPolicy)
        with pytest.raises(ValueError, match="unknown routing policy"):
            get_policy("fifo")
        with pytest.raises(TypeError, match="pick"):
            get_policy(object())


# ---------------------------------------------------------------------------
# NodePool probing — the GetLoad / TCP-probe lanes
# ---------------------------------------------------------------------------


class TestNodePoolProbing:
    def test_concurrent_getload_probing_npwire_and_npproto(self):
        """One probe sweep over a MIXED pool — an npwire-JSON node, a
        reference-protobuf GetLoad node, and a dead port — probed in
        parallel: both reply formats parse into load dicts, the dead
        replica records a breaker failure, live ones stay closed."""
        from pytensor_federated_tpu.service.server import (
            ArraysToArraysService,
            serve,
        )

        async def main():
            p_npwire, p_npproto = _free_port(), _free_port()
            dead = _dead_port()
            s1 = await serve(
                None,
                "127.0.0.1",
                p_npwire,
                service=ArraysToArraysService(_quad, getload_wire="npwire"),
            )
            s2 = await serve(
                None,
                "127.0.0.1",
                p_npproto,
                service=ArraysToArraysService(
                    _quad, getload_wire="npproto"
                ),
            )
            pool = NodePool(
                [
                    ("127.0.0.1", p_npwire),
                    ("127.0.0.1", p_npproto),
                    ("127.0.0.1", dead),
                ],
                probe_timeout_s=2.0,
                breaker_kwargs=dict(failure_threshold=1, backoff_s=5.0),
            )
            try:
                up = await pool.probe_once_async()
                assert up == 2
                r_wire = pool.replica_at("127.0.0.1", p_npwire)
                r_proto = pool.replica_at("127.0.0.1", p_npproto)
                r_dead = pool.replica_at("127.0.0.1", dead)
                # npwire JSON reply: full enriched load
                assert r_wire.load["n_clients"] == 0
                assert "batch" in r_wire.load  # capability advertised
                # npproto reply: the reference's three fields
                assert r_proto.load["n_clients"] == 0
                assert "percent_cpu" in r_proto.load
                # the dead replica tripped on its failed probe
                assert r_dead.load is None
                assert r_dead.breaker.state == "open"
                assert r_wire.breaker.state == "closed"
                assert r_proto.breaker.state == "closed"
                # availability reflects the sweep
                avail = {r.address for r in pool.available_replicas()}
                assert avail == {r_wire.address, r_proto.address}
            finally:
                await s1.stop(None)
                await s2.stop(None)

        asyncio.run(main())

    def test_parallel_probe_sweeps_are_thread_safe(self):
        """Several concurrent sweeps against one live npwire node must
        all parse (regression: the pool's replica/load state is shared
        across the probing thread and callers)."""
        from pytensor_federated_tpu.service.server import serve

        async def main():
            port = _free_port()
            server = await serve(_quad, "127.0.0.1", port)
            pool = NodePool([("127.0.0.1", port)], probe_timeout_s=2.0)
            try:
                ups = await asyncio.gather(
                    *(pool.probe_once_async() for _ in range(8))
                )
                assert all(u == 1 for u in ups)
                assert pool.replicas[0].load["n_clients"] == 0
            finally:
                await server.stop(None)

        asyncio.run(main())

    def test_stale_load_eviction(self):
        replica = NodePool(
            [("127.0.0.1", 1)], load_stale_s=0.05
        ).replicas[0]
        replica.record_load({"n_clients": 3})
        assert replica.queue_depth() == 3.0
        time.sleep(0.06)
        # stale: the advertised load stops informing routing AND the
        # snapshot is evicted, so a later read cannot resurrect it
        assert replica.queue_depth() is None
        assert replica.load is None

    def test_queue_depth_prefers_batcher_then_rpc_then_clients(self):
        replica = NodePool([("127.0.0.1", 1)]).replicas[0]
        replica.record_load(
            {"n_clients": 9, "rpc": {"inflight": 4},
             "batch": {"queue_depth": 2, "max_batch": 32}}
        )
        assert replica.queue_depth() == 2.0
        replica.record_load({"n_clients": 9, "rpc": {"inflight": 4}})
        assert replica.queue_depth() == 4.0
        replica.record_load({"n_clients": 9})
        assert replica.queue_depth() == 9.0

    def test_tcp_zero_item_probe_is_the_health_check(self):
        """The zero-item batch frame (the PR-3 capability handshake)
        doubles as the TCP liveness probe: a live node passes, a dead
        port fails, and a pool on transport="tcp" routes the verdicts
        into its breakers."""
        from pytensor_federated_tpu.service import serve_tcp_once

        started = threading.Event()
        box = {}
        threading.Thread(
            target=serve_tcp_once,
            args=(_quad,),
            daemon=True,
            kwargs=dict(
                ready_callback=lambda p: (box.update(p=p), started.set()),
                max_connections=4,
            ),
        ).start()
        assert started.wait(10)
        live, dead = box["p"], _dead_port()
        assert _tcp_probe("127.0.0.1", live, timeout=2.0)
        assert not _tcp_probe("127.0.0.1", dead, timeout=0.5)

        pool = NodePool(
            [("127.0.0.1", live), ("127.0.0.1", dead)],
            transport="tcp",
            probe_timeout_s=1.0,
            breaker_kwargs=dict(failure_threshold=1, backoff_s=5.0),
        )
        assert pool.probe_once() == 1
        assert pool.replica_at("127.0.0.1", live).breaker.state == "closed"
        assert pool.replica_at("127.0.0.1", dead).breaker.state == "open"
        # TCP advertises liveness only: no load schema on this lane
        assert pool.replica_at("127.0.0.1", live).load == {}
        assert pool.replica_at("127.0.0.1", live).queue_depth() is None

    def test_probe_success_restores_tripped_breaker(self):
        """Background probing is the recovery lane: a replica that died
        (breaker open) and came back is restored by the next sweep."""
        from pytensor_federated_tpu.service.server import serve

        async def main():
            port = _free_port()
            pool = NodePool(
                [("127.0.0.1", port)],
                probe_timeout_s=2.0,
                breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
            )
            assert await pool.probe_once_async() == 0
            assert pool.replicas[0].breaker.state == "open"
            server = await serve(_quad, "127.0.0.1", port)
            try:
                # Retry under a deadline: one probe can time out on a
                # loaded machine while the fresh server warms up.
                deadline = time.time() + 30
                while await pool.probe_once_async() != 1:
                    assert time.time() < deadline, "server never probed up"
                    await asyncio.sleep(0.2)
                assert pool.replicas[0].breaker.state == "closed"
            finally:
                await server.stop(None)

        asyncio.run(main())

    def test_background_probe_thread_and_late_add_remove(self):
        from pytensor_federated_tpu.service.server import serve

        async def main():
            port = _free_port()
            server = await serve(_quad, "127.0.0.1", port)
            pool = NodePool(probe_interval_s=0.05, probe_timeout_s=1.0)
            try:
                assert len(pool) == 0
                pool.add_replica("127.0.0.1", port)  # late add
                pool.start()
                deadline = time.time() + 10
                while pool.replicas[0].load is None:
                    assert time.time() < deadline, "probe loop never ran"
                    await asyncio.sleep(0.05)
                assert pool.replicas[0].breaker.state == "closed"
                pool.remove_replica("127.0.0.1", port)  # late remove
                assert len(pool) == 0
            finally:
                pool.close()
                await server.stop(None)

        asyncio.run(main())


# ---------------------------------------------------------------------------
# PooledArraysClient — routing, failover, hedging
# ---------------------------------------------------------------------------


class TestPooledClient:
    def test_failover_exactly_once_and_breaker_trip(self):
        """2 live + 1 dead replica: every request of a spread window
        gets exactly one correct reply; the dead replica's breaker
        trips; a repeat batch avoids it entirely."""
        from pytensor_federated_tpu.service.server import serve

        async def main():
            p1, p2, dead = _free_port(), _free_port(), _dead_port()
            s1 = await serve(_quad, "127.0.0.1", p1)
            s2 = await serve(_quad, "127.0.0.1", p2)
            pool = NodePool(
                [
                    ("127.0.0.1", p1),
                    ("127.0.0.1", p2),
                    ("127.0.0.1", dead),
                ],
                breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
            )
            client = PooledArraysClient(pool)
            try:
                reqs = [
                    (np.array([float(i), 5.0], np.float32),)
                    for i in range(48)
                ]
                res = await client.evaluate_many_async(reqs, window=8)
                assert len(res) == len(reqs)
                for i, out in enumerate(res):
                    assert out is not None
                    np.testing.assert_allclose(
                        float(np.asarray(out[0])),
                        -((i - 3.0) ** 2 + 4.0),
                        rtol=1e-6,
                    )
                assert (
                    pool.replica_at("127.0.0.1", dead).breaker.state
                    == "open"
                )
                # Second pass: dead replica no longer admitted
                res2 = await client.evaluate_many_async(reqs, window=8)
                assert all(r is not None for r in res2)
            finally:
                await s1.stop(None)
                await s2.stop(None)

        asyncio.run(main())

    def test_single_evaluate_failover(self):
        from pytensor_federated_tpu.service.server import serve

        async def main():
            live, dead = _free_port(), _dead_port()
            server = await serve(_quad, "127.0.0.1", live)
            pool = NodePool(
                [("127.0.0.1", dead), ("127.0.0.1", live)],
                policy="round_robin",  # first pick = dead, forcing failover
                breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
            )
            client = PooledArraysClient(pool)
            try:
                out = await client.evaluate_async(
                    np.array([1.0, 5.0], np.float32)
                )
                np.testing.assert_allclose(float(np.asarray(out[0])), -8.0)
                assert (
                    pool.replica_at("127.0.0.1", dead).breaker.state
                    == "open"
                )
            finally:
                await server.stop(None)

        asyncio.run(main())

    def test_all_replicas_down_raises(self):
        async def main():
            pool = NodePool(
                [("127.0.0.1", _dead_port())],
                breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
            )
            client = PooledArraysClient(pool)
            with pytest.raises((ConnectionError, OSError)):
                await client.evaluate_async(np.zeros(2, np.float32))
            # pool exhausted on a later call with the breaker open
            with pytest.raises(ConnectionError, match="no available"):
                await client.evaluate_async(np.zeros(2, np.float32))

        asyncio.run(main())

    def test_server_error_raises_without_breaker_hit(self):
        """A deterministic compute error must surface unchanged and
        must NOT trip the (healthy) replica's breaker or fail over."""
        from pytensor_federated_tpu.service.server import serve

        def poison(x):
            raise ValueError("poison input")

        async def main():
            port = _free_port()
            server = await serve(poison, "127.0.0.1", port)
            pool = NodePool(
                [("127.0.0.1", port)],
                breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
            )
            client = PooledArraysClient(pool)
            try:
                for _ in range(3):
                    with pytest.raises(RuntimeError, match="poison"):
                        await client.evaluate_async(
                            np.zeros(2, np.float32)
                        )
                assert pool.replicas[0].breaker.state == "closed"
            finally:
                await server.stop(None)

        asyncio.run(main())

    def test_hedged_request_cuts_past_a_slow_replica(self):
        """Slow primary + fast sibling: the hedge fires at the latency
        quantile deadline, the fast replica's reply wins, wall time
        stays far below the slow compute."""
        from pytensor_federated_tpu.routing.pool import _POOL_HEDGES
        from pytensor_federated_tpu.service.server import serve

        slow_delay = 0.8

        def slow_quad(x):
            time.sleep(slow_delay)
            return _quad(x)

        async def main():
            p_slow, p_fast = _free_port(), _free_port()
            s1 = await serve(slow_quad, "127.0.0.1", p_slow)
            s2 = await serve(_quad, "127.0.0.1", p_fast)
            pool = NodePool(
                [("127.0.0.1", p_slow), ("127.0.0.1", p_fast)],
                policy="round_robin",  # deterministic: first pick = slow
            )
            client = PooledArraysClient(
                pool, hedge=True, hedge_quantile=0.5
            )
            # Arm the hedge deadline estimator with observed-fast calls
            for _ in range(16):
                client._latency.record(0.02)
            won0 = _POOL_HEDGES.labels(outcome="won").value
            try:
                t0 = time.perf_counter()
                out = await client.evaluate_async(
                    np.array([1.0, 5.0], np.float32)
                )
                wall = time.perf_counter() - t0
                np.testing.assert_allclose(float(np.asarray(out[0])), -8.0)
                assert wall < slow_delay / 2, (
                    f"hedge did not cut past the slow replica: {wall}s"
                )
                assert _POOL_HEDGES.labels(outcome="won").value == won0 + 1
                kinds = [e["kind"] for e in flightrec.events()]
                assert "pool.hedge" in kinds
            finally:
                await s1.stop(None)
                await s2.stop(None)

        asyncio.run(main())

    def test_partial_pass_full_window_and_server_error(self):
        """evaluate_many_partial_async on a healthy node: complete
        results + no exc; a mid-window deterministic error raises out
        of the partial pass (failover must not swallow it)."""
        from pytensor_federated_tpu.service.client import (
            ArraysToArraysServiceClient,
        )
        from pytensor_federated_tpu.service.server import serve

        def compute(x):
            x = np.asarray(x)
            if x.shape == (2,):
                raise ValueError("poison shape")
            return [np.asarray(float(np.sum(x)))]

        async def main():
            port = _free_port()
            server = await serve(compute, "127.0.0.1", port)
            client = ArraysToArraysServiceClient(
                "127.0.0.1", port, retries=0
            )
            try:
                results, exc = await client.evaluate_many_partial_async(
                    [(np.ones(i),) for i in (1, 3, 4)], window=4
                )
                assert exc is None
                assert [float(np.asarray(r[0])) for r in results] == [
                    1.0,
                    3.0,
                    4.0,
                ]
                with pytest.raises(RuntimeError, match="poison shape"):
                    await client.evaluate_many_partial_async(
                        [(np.ones(1),), (np.ones(2),), (np.ones(3),)],
                        window=4,
                    )
            finally:
                await server.stop(None)

        asyncio.run(main())

    def test_tcp_pool_end_to_end(self):
        """The pool above the TCP transport: spread + failover against
        one live serve_tcp_once node and one dead port, sync surface."""
        from pytensor_federated_tpu.service import serve_tcp_once

        started = threading.Event()
        box = {}
        threading.Thread(
            target=serve_tcp_once,
            args=(_quad,),
            daemon=True,
            kwargs=dict(
                ready_callback=lambda p: (box.update(p=p), started.set()),
                max_connections=2,
            ),
        ).start()
        assert started.wait(10)
        dead = _dead_port()
        pool = NodePool(
            [("127.0.0.1", box["p"]), ("127.0.0.1", dead)],
            transport="tcp",
            breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
        )
        client = PooledArraysClient(pool)
        try:
            reqs = [
                (np.array([float(i), 5.0]),) for i in range(24)
            ]
            res = client.evaluate_many(reqs, window=6)
            for i, out in enumerate(res):
                np.testing.assert_allclose(
                    float(np.asarray(out[0])), -((i - 3.0) ** 2 + 4.0)
                )
            out = client.evaluate(np.array([1.0, 5.0]))
            np.testing.assert_allclose(float(np.asarray(out[0])), -8.0)
            assert (
                pool.replica_at("127.0.0.1", dead).breaker.state == "open"
            )
        finally:
            client.close() if client._owns_pool else pool.close()

    def test_owned_pool_from_addresses(self):
        client = PooledArraysClient(
            [("127.0.0.1", 1), ("127.0.0.1", 2)],
            breaker_kwargs=dict(failure_threshold=1),
        )
        assert client._owns_pool and len(client.pool) == 2
        client.close()
        assert len(client.pool) == 0
        with pytest.raises(ValueError, match="pool_kwargs"):
            PooledArraysClient(NodePool(), probe_interval_s=1.0)


# ---------------------------------------------------------------------------
# Elastic sampling: pool shrink as a recovery tier
# ---------------------------------------------------------------------------


class TestElasticPoolTier:
    def test_pool_recovery_tier_runs_before_remesh(self, tmp_path):
        """A segment failing with a transport error triggers the pool
        recovery tier: the pool is probed NOW, the dead replica's
        breaker trips, and sampling resumes over the rebuilt logp —
        no mesh involved, no process restart."""
        import jax
        import jax.numpy as jnp

        from pytensor_federated_tpu.samplers import elastic_sample

        flightrec.clear()
        pool = NodePool(
            [("127.0.0.1", _dead_port())],
            probe_timeout_s=0.5,
            breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
        )
        builds = []

        def build_logp(mesh):
            builds.append(mesh)
            if len(builds) == 1:
                def dead_node_logp(params):
                    raise ConnectionError("replica gone mid-segment")

                return dead_node_logp
            return lambda params: -0.5 * jnp.sum(params["x"] ** 2)

        res = elastic_sample(
            build_logp,
            {"x": jnp.zeros(2)},
            key=jax.random.PRNGKey(0),
            checkpoint_path=str(tmp_path / "run.ckpt"),
            node_pool=pool,
            num_warmup=20,
            num_samples=20,
            num_chains=1,
            checkpoint_every=10,
        )
        assert np.asarray(res.samples["x"]).shape[1] == 20
        assert len(builds) == 2  # initial + one post-recovery rebuild
        assert pool.replicas[0].breaker.state == "open"
        kinds = [e["kind"] for e in flightrec.events()]
        assert "sampler.pool_recovered" in kinds
        rec = next(
            e for e in flightrec.events()
            if e["kind"] == "sampler.pool_recovered"
        )
        assert rec["healthy_replicas"] == 0
        assert rec["total_replicas"] == 1


# ---------------------------------------------------------------------------
# tools/metrics_dump.py --pool: per-replica health from the exposition lane
# ---------------------------------------------------------------------------


class TestMetricsDumpPoolView:
    def test_pool_view_renders_replica_rows(self, capsys):
        import importlib.util
        import pathlib

        from pytensor_federated_tpu.telemetry.export import start_exporter

        spec = importlib.util.spec_from_file_location(
            "metrics_dump",
            pathlib.Path(__file__).resolve().parent.parent
            / "tools"
            / "metrics_dump.py",
        )
        metrics_dump = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(metrics_dump)

        # Populate the pool gauges the way a live pool does.
        pool = NodePool(
            [("127.0.0.1", 41001), ("127.0.0.1", 41002)],
            breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
        )
        pool.replicas[0].record_load(
            {"n_clients": 0, "batch": {"queue_depth": 2, "max_batch": 32}}
        )
        pool.replicas[0].record_latency(0.0042)
        pool.replicas[1].breaker.record_failure()  # trips: threshold 1
        pool._refresh_state_gauges()

        exporter = start_exporter("127.0.0.1", 0)
        try:
            rc = metrics_dump.main(
                ["--port", str(exporter.port), "--pool"]
            )
            out = capsys.readouterr().out
            assert rc == 0
            assert "127.0.0.1:41001" in out and "127.0.0.1:41002" in out
            row1 = next(
                l for l in out.splitlines() if "127.0.0.1:41001" in l
            )
            row2 = next(
                l for l in out.splitlines() if "127.0.0.1:41002" in l
            )
            assert "yes" in row1 and "2" in row1 and "4.20" in row1
            assert "NO" in row2
            assert "breakers:" in out
        finally:
            exporter.close()


# ---------------------------------------------------------------------------
# Review regressions: half-open token hygiene, p2c local-inflight fallback
# ---------------------------------------------------------------------------


class TestReviewRegressions:
    def test_p2c_falls_back_to_local_inflight(self):
        import random

        p2c = PowerOfTwoChoicesPolicy(rng=random.Random(0))
        busy = _FakeReplica("busy")   # no advertised load (TCP lane)
        idle = _FakeReplica("idle")
        busy.inflight, idle.inflight = 6, 0
        picks = [p2c.pick([busy, idle], 1)[0].address for _ in range(25)]
        assert all(p == "idle" for p in picks)

    def test_p2c_known_zero_depth_beats_unknown_with_inflight(self):
        import random

        p2c = PowerOfTwoChoicesPolicy(rng=random.Random(0))
        known = _FakeReplica("known", depth=0)
        unknown = _FakeReplica("unknown")  # stale/no load, 1 in flight
        unknown.inflight = 1
        picks = [
            p2c.pick([known, unknown], 1)[0].address for _ in range(25)
        ]
        assert all(p == "known" for p in picks)

    def test_breaker_release_returns_probe_token(self):
        b = CircuitBreaker(
            failure_threshold=1, backoff_s=0.01, jitter_frac=0.0
        )
        b.record_failure()
        time.sleep(0.015)
        assert b.acquire()      # claims the half-open token
        assert not b.available()
        b.release()             # abandoned call gives it back
        assert b.available() and b.acquire()

    def test_half_open_probe_serving_a_server_error_closes_breaker(self):
        """A deterministic compute error on the half-open probe call
        proves the replica is SERVING: the breaker must close (token
        resolved), not stay parked in half-open forever — the leak a
        pool without a background probe loop could never recover from."""
        from pytensor_federated_tpu.service.server import serve

        def poison(x):
            raise ValueError("poison input")

        async def main():
            port = _free_port()
            server = await serve(poison, "127.0.0.1", port)
            pool = NodePool(
                [("127.0.0.1", port)],
                breaker_kwargs=dict(
                    failure_threshold=1, backoff_s=0.05, jitter_frac=0.0
                ),
            )
            client = PooledArraysClient(pool)
            replica = pool.replicas[0]
            try:
                replica.breaker.record_failure()  # trip (threshold 1)
                assert replica.breaker.state == "open"
                await asyncio.sleep(0.08)
                assert replica.breaker.state == "half_open"
                with pytest.raises(RuntimeError, match="poison"):
                    await client.evaluate_async(np.zeros(2, np.float32))
                assert replica.breaker.state == "closed"
                # and the pool keeps serving (no parked token)
                with pytest.raises(RuntimeError, match="poison"):
                    await client.evaluate_async(np.zeros(2, np.float32))
            finally:
                await server.stop(None)

        asyncio.run(main())
