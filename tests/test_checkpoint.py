"""Checkpoint/resume subsystem (checkpoint.py).

Key property: a run interrupted at any chunk boundary and resumed
produces BIT-IDENTICAL draws to an uninterrupted run (the durability
analog of the reference's stateless-retry semantics, reference:
service.py:408-416 — there a lost call is simply re-sent; here a lost
process is re-started from disk).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.checkpoint import (
    load_pytree,
    sample_checkpointed,
    save_pytree,
)


class TestPytreeSnapshot:
    def test_roundtrip(self, tmp_path):
        tree = {
            "a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "b": (jnp.zeros(()), jnp.ones((4,), jnp.int32)),
        }
        p = str(tmp_path / "ck.npz")
        save_pytree(p, tree, {"step": 7})
        got, meta = load_pytree(p, tree)
        assert meta == {"step": 7}
        leaves = jax.tree_util.tree_leaves
        for a, b in zip(leaves(tree), leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_structure_mismatch_raises(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        save_pytree(p, {"a": jnp.zeros(2), "b": jnp.zeros(3)})
        with pytest.raises(ValueError, match="structure mismatch"):
            load_pytree(p, {"a": jnp.zeros(2)})

    def test_atomic_overwrite(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        save_pytree(p, {"a": jnp.zeros(2)}, {"v": 1})
        save_pytree(p, {"a": jnp.ones(2)}, {"v": 2})
        got, meta = load_pytree(p, {"a": jnp.zeros(2)})
        assert meta["v"] == 2
        np.testing.assert_array_equal(np.asarray(got["a"]), np.ones(2))
        # no stray temp files
        assert os.listdir(tmp_path) == ["ck.npz"]


def _logp(params):
    x = params["x"]
    return -0.5 * jnp.sum(x**2)


class TestSampleCheckpointed:
    def test_resume_bit_identical(self, tmp_path):
        kwargs = dict(
            key=jax.random.PRNGKey(0),
            num_warmup=100,
            num_samples=60,
            num_chains=2,
            checkpoint_every=20,
            kernel="nuts",
            max_depth=5,
        )
        init = {"x": jnp.zeros(3)}

        # Uninterrupted run.
        p1 = str(tmp_path / "run1.npz")
        res_full = sample_checkpointed(
            _logp, init, checkpoint_path=p1, **kwargs
        )

        # Interrupted run: stop after chunk 1 by monkeypatching range?
        # Simpler: run once with num_samples=20 config... instead simulate
        # interruption by copying the chunk-1 checkpoint: run full into p2,
        # capturing the intermediate file after the first chunk.
        p2 = str(tmp_path / "run2.npz")
        import pytensor_federated_tpu.checkpoint as ck

        saved_states = []
        orig_save = ck.save_pytree

        def spy_save(path, tree, metadata=None):
            orig_save(path, tree, metadata)
            if path == p2:
                saved_states.append(metadata["chunks_done"])
            # Simulate a crash right after chunk 1 persists.
            if path == p2 and metadata and metadata.get("chunks_done") == 1:
                raise KeyboardInterrupt

        ck.save_pytree = spy_save
        try:
            with pytest.raises(KeyboardInterrupt):
                sample_checkpointed(_logp, init, checkpoint_path=p2, **kwargs)
        finally:
            ck.save_pytree = orig_save

        assert saved_states[-1] == 1  # crashed after first chunk
        # Resume: same call, same args.
        res_resumed = sample_checkpointed(
            _logp, init, checkpoint_path=p2, **kwargs
        )
        np.testing.assert_array_equal(
            np.asarray(res_full.samples["x"]),
            np.asarray(res_resumed.samples["x"]),
        )
        np.testing.assert_array_equal(
            np.asarray(res_full.stats["accept_prob"]),
            np.asarray(res_resumed.stats["accept_prob"]),
        )

    def test_config_mismatch_restarts(self, tmp_path):
        p = str(tmp_path / "run.npz")
        init = {"x": jnp.zeros(2)}
        sample_checkpointed(
            _logp,
            init,
            key=jax.random.PRNGKey(1),
            num_warmup=50,
            num_samples=20,
            num_chains=2,
            checkpoint_every=10,
            checkpoint_path=p,
        )
        # Different config: stale checkpoint must be ignored, not crash.
        res = sample_checkpointed(
            _logp,
            init,
            key=jax.random.PRNGKey(1),
            num_warmup=50,
            num_samples=30,
            num_chains=2,
            checkpoint_every=10,
            checkpoint_path=p,
        )
        assert res.samples["x"].shape == (2, 30, 2)

    def test_different_key_restarts(self, tmp_path):
        """Resuming under a different RNG key must NOT stitch runs."""
        p = str(tmp_path / "run.npz")
        init = {"x": jnp.zeros(2)}
        kw = dict(
            num_warmup=50,
            num_samples=20,
            num_chains=2,
            checkpoint_every=10,
            checkpoint_path=p,
        )
        r1 = sample_checkpointed(_logp, init, key=jax.random.PRNGKey(0), **kw)
        r2 = sample_checkpointed(_logp, init, key=jax.random.PRNGKey(1), **kw)
        # Different keys -> fully re-run -> different draws.
        assert not np.array_equal(
            np.asarray(r1.samples["x"]), np.asarray(r2.samples["x"])
        )

    def test_posterior_accuracy(self, tmp_path):
        """Std-normal target: moments correct through the chunked path."""
        res = sample_checkpointed(
            _logp,
            {"x": jnp.zeros(2)},
            key=jax.random.PRNGKey(2),
            num_warmup=200,
            num_samples=400,
            num_chains=2,
            checkpoint_every=100,
            checkpoint_path=str(tmp_path / "acc.npz"),
        )
        xs = np.asarray(res.samples["x"]).reshape(-1, 2)
        np.testing.assert_allclose(xs.mean(0), 0.0, atol=0.15)
        np.testing.assert_allclose(xs.std(0), 1.0, atol=0.2)


class TestConfigVersionUpgrade:
    """A checkpoint written before a config key existed must still
    resume when the current run uses that key's default (round-3
    ADVICE: the silent version-upgrade discard)."""

    KW = dict(
        num_warmup=50,
        num_samples=20,
        num_chains=2,
        checkpoint_every=10,
    )

    def _strip_key(self, path, drop="dense_mass"):
        """Rewrite the stored meta as a pre-upgrade checkpoint would
        have written it: config lacking ``drop``."""
        # The state template here matches the run in these tests
        # (2 chains, dim=2, diagonal mass).
        like = {
            "x": jnp.zeros((2, 2)),
            "logp": jnp.zeros((2,)),
            "grad": jnp.zeros((2, 2)),
            "step_size": jnp.zeros((2,)),
            "inv_mass": jnp.zeros((2, 2)),
        }
        state, meta = load_pytree(path, like)
        assert drop in meta["config"]
        del meta["config"][drop]
        save_pytree(path, state, meta)

    def test_missing_defaulted_key_resumes(self, tmp_path):
        p = str(tmp_path / "run.npz")
        init = {"x": jnp.zeros(2)}
        sample_checkpointed(
            _logp, init, key=jax.random.PRNGKey(3), checkpoint_path=p,
            **self.KW,
        )
        self._strip_key(p)
        # Tamper a chunk's draws with a sentinel: if the rerun resumes
        # (as it must), the sentinel shows up in its output; if it
        # silently restarted, it would not.
        cp = p + ".chunk0000.npz"
        chunk_like = {
            "draws": jnp.zeros((2, 10, 2)),
            "accept_prob": jnp.zeros((2, 10)),
            "diverging": jnp.zeros((2, 10), bool),
        }
        chunk, cmeta = load_pytree(cp, chunk_like)
        chunk["draws"] = jnp.full_like(chunk["draws"], 1234.5)
        save_pytree(cp, chunk, cmeta)
        res = sample_checkpointed(
            _logp, init, key=jax.random.PRNGKey(3), checkpoint_path=p,
            **self.KW,
        )
        assert np.all(np.asarray(res.samples["x"])[:, :10] == 1234.5)

    def test_missing_key_nondefault_run_restarts(self, tmp_path, caplog):
        import logging

        p = str(tmp_path / "run.npz")
        init = {"x": jnp.zeros(2)}
        sample_checkpointed(
            _logp, init, key=jax.random.PRNGKey(3), checkpoint_path=p,
            **self.KW,
        )
        self._strip_key(p)
        # Current run wants dense mass: the old checkpoint is NOT
        # compatible, and the discard must be logged, not silent.
        with caplog.at_level(
            logging.WARNING, logger="pytensor_federated_tpu.checkpoint"
        ):
            res = sample_checkpointed(
                _logp, init, key=jax.random.PRNGKey(3), checkpoint_path=p,
                dense_mass=True, **self.KW,
            )
        assert res.samples["x"].shape == (2, 20, 2)
        assert any("discarding checkpoint" in r.message for r in caplog.records)

    def test_extra_stored_key_restarts(self, tmp_path):
        """A checkpoint from a NEWER version (stored config has a key
        this version does not know) must restart, not resume."""
        p = str(tmp_path / "run.npz")
        init = {"x": jnp.zeros(2)}
        sample_checkpointed(
            _logp, init, key=jax.random.PRNGKey(3), checkpoint_path=p,
            **self.KW,
        )
        like = {
            "x": jnp.zeros((2, 2)),
            "logp": jnp.zeros((2,)),
            "grad": jnp.zeros((2, 2)),
            "step_size": jnp.zeros((2,)),
            "inv_mass": jnp.zeros((2, 2)),
        }
        state, meta = load_pytree(p, like)
        meta["config"]["from_the_future"] = 1
        save_pytree(p, state, meta)
        res = sample_checkpointed(
            _logp, init, key=jax.random.PRNGKey(3), checkpoint_path=p,
            **self.KW,
        )
        assert res.samples["x"].shape == (2, 20, 2)
