"""Count-data GLM family: golden-model equivalence + inference accuracy.

Same strategy as the other families (SURVEY §4): scipy is the golden
oracle for the observation logpmfs, a hand-built dense jnp expression
is the oracle for the full posterior, MAP must recover the simulation
truth, and a short NUTS run must converge with calibrated posteriors.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.stats

from pytensor_federated_tpu.models.countdata import (
    FederatedNegBinGLM,
    FederatedPoissonGLM,
    generate_count_data,
    negbin_logpmf,
    poisson_logpmf,
)


class TestLogpmfGolden:
    def test_poisson_matches_scipy(self):
        rng = np.random.default_rng(0)
        y = rng.poisson(3.0, size=50).astype(np.float32)
        eta = rng.normal(0.5, 1.0, size=50).astype(np.float32)
        ours = np.asarray(poisson_logpmf(jnp.asarray(y), jnp.asarray(eta)))
        golden = scipy.stats.poisson.logpmf(y, np.exp(eta))
        np.testing.assert_allclose(ours, golden, rtol=2e-4, atol=2e-4)

    def test_negbin_matches_scipy(self):
        rng = np.random.default_rng(1)
        y = rng.poisson(3.0, size=50).astype(np.float32)
        eta = rng.normal(0.5, 0.8, size=50).astype(np.float32)
        phi = 3.5
        ours = np.asarray(
            negbin_logpmf(jnp.asarray(y), jnp.asarray(eta), phi)
        )
        # scipy nbinom: n=phi, p=phi/(phi+mu)
        mu = np.exp(eta)
        golden = scipy.stats.nbinom.logpmf(y, phi, phi / (phi + mu))
        np.testing.assert_allclose(ours, golden, rtol=2e-4, atol=2e-4)

    def test_negbin_limits_to_poisson(self):
        # phi large enough that NB2 ~ Poisson (truncation error
        # O(y^2/phi) ~ 8e-3) but small enough that f32
        # gammaln(y+phi) - gammaln(phi) has not yet lost all precision
        # to cancellation (gammaln(1e4) ~ 8e4, f32 abs err ~ 5e-3).
        y = jnp.asarray([0.0, 1.0, 4.0, 9.0])
        eta = jnp.asarray([-1.0, 0.0, 1.0, 2.0])
        nb = negbin_logpmf(y, eta, 1e4)
        po = poisson_logpmf(y, eta)
        np.testing.assert_allclose(np.asarray(nb), np.asarray(po), atol=5e-2)


class TestPosteriorGolden:
    def test_federated_logp_equals_dense_expression(self):
        data, _ = generate_count_data(4, n_obs=24, n_features=3)
        m = FederatedPoissonGLM(data)
        params = {
            "w": jnp.asarray([0.1, -0.2, 0.3]),
            "b0": jnp.asarray(0.5),
            "log_tau": jnp.asarray(-0.5),
            "b_raw": jnp.asarray([0.3, -0.1, 0.2, 0.0]),
        }
        (X, y), mask = data.tree()
        tau = jnp.exp(params["log_tau"])
        b = params["b0"] + tau * params["b_raw"]
        eta = jnp.einsum("snd,d->sn", X, params["w"]) + b[:, None]
        dense = jnp.sum(poisson_logpmf(y, eta) * mask) + m.prior_logp(params)
        np.testing.assert_allclose(
            float(m.logp(params)), float(dense), rtol=1e-5
        )

    def test_grads_against_dense_autodiff(self):
        data, _ = generate_count_data(4, n_obs=24, n_features=3)
        m = FederatedPoissonGLM(data)
        p0 = m.init_params()
        v, g = m.logp_and_grad(p0)
        (X, y), mask = data.tree()

        def dense(params):
            tau = jnp.exp(params["log_tau"])
            b = params["b0"] + tau * params["b_raw"]
            eta = jnp.einsum("snd,d->sn", X, params["w"]) + b[:, None]
            return jnp.sum(poisson_logpmf(y, eta) * mask) + m.prior_logp(
                params
            )

        vd, gd = jax.value_and_grad(dense)(p0)
        np.testing.assert_allclose(float(v), float(vd), rtol=1e-5)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(gd[k]), rtol=1e-4, atol=1e-5
            )


class TestInference:
    def test_poisson_map_recovers_truth(self):
        data, truth = generate_count_data(8, n_obs=96, n_features=3, seed=5)
        m = FederatedPoissonGLM(data)
        est = m.find_map()
        np.testing.assert_allclose(
            np.asarray(est["w"]), truth["w"], atol=0.15
        )
        assert abs(float(est["b0"]) - truth["b0"]) < 0.3

    def test_negbin_map_recovers_truth(self):
        data, truth = generate_count_data(
            8, n_obs=128, n_features=3, dispersion=4.0, seed=6
        )
        m = FederatedNegBinGLM(data)
        est = m.find_map()
        np.testing.assert_allclose(
            np.asarray(est["w"]), truth["w"], atol=0.2
        )

    def test_poisson_nuts_converges(self):
        data, truth = generate_count_data(4, n_obs=64, n_features=2, seed=7)
        m = FederatedPoissonGLM(data)
        res = m.sample(
            key=jax.random.PRNGKey(2),
            num_warmup=300,
            num_samples=300,
            num_chains=2,
        )
        summ = res.summary()
        assert float(np.max(np.asarray(summ["rhat"]["w"]))) < 1.05
        w_mean = np.asarray(res.samples["w"]).mean(axis=(0, 1))
        np.testing.assert_allclose(w_mean, truth["w"], atol=0.2)


@pytest.mark.parametrize("cls", [FederatedPoissonGLM, FederatedNegBinGLM])
def test_on_mesh(cls, devices8):
    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"shards": 8}, devices=devices8)
    data, _ = generate_count_data(8, n_obs=32, n_features=2, seed=9)
    m_mesh = cls(data, mesh=mesh)
    m_local = cls(data)
    p0 = m_local.init_params()
    # psum reduction order differs from the single-device flat sum;
    # with gammaln-sized terms the f32 divergence can reach ~1e-4 rel.
    np.testing.assert_allclose(
        float(m_mesh.logp(p0)), float(m_local.logp(p0)), rtol=5e-4
    )
    v1, g1 = m_mesh.logp_and_grad(p0)
    v2, g2 = m_local.logp_and_grad(p0)
    for k in g1:
        np.testing.assert_allclose(
            np.asarray(g1[k]), np.asarray(g2[k]), rtol=1e-3, atol=1e-4
        )


def test_poisson_overflow_stays_finite():
    # Extreme proposals (eta >> f32 exp range) must give a huge negative
    # logp with FINITE gradients — not -inf/NaN that poisons the shard
    # sum through 0 * inf against zero design entries or padded rows.
    X = jnp.asarray([[0.0, 1.0], [1.0, 0.0]])
    y = jnp.asarray([0.0, 3.0])

    def lp(w):
        return jnp.sum(poisson_logpmf(y, X @ w))

    w_extreme = jnp.asarray([200.0, 200.0])
    v, g = jax.value_and_grad(lp)(w_extreme)
    assert np.isfinite(float(v)) and float(v) < -1e30
    assert np.all(np.isfinite(np.asarray(g)))


class TestZeroInflated:
    def test_pi_zero_reduces_to_base(self):
        """logit_pi -> -inf turns ZIP into exactly Poisson (and ZINB
        into NB) — the mixture must vanish cleanly in log space."""
        import jax.numpy as jnp

        from pytensor_federated_tpu.models.countdata import (
            negbin_logpmf,
            poisson_logpmf,
            zero_inflate_logpmf,
        )

        y = jnp.asarray([0.0, 1.0, 3.0, 7.0])
        eta = jnp.asarray([0.2, -0.5, 1.0, 0.3])
        base = poisson_logpmf(y, eta)
        np.testing.assert_allclose(
            np.asarray(zero_inflate_logpmf(y, base, -40.0)),
            np.asarray(base), rtol=1e-6,
        )
        base_nb = negbin_logpmf(y, eta, 3.0)
        np.testing.assert_allclose(
            np.asarray(zero_inflate_logpmf(y, base_nb, -40.0)),
            np.asarray(base_nb), rtol=1e-6,
        )

    def test_zero_probability_mixture(self):
        """At y=0 the pmf must be exactly pi + (1-pi)*base(0)."""
        import jax.numpy as jnp

        from pytensor_federated_tpu.models.countdata import (
            poisson_logpmf,
            zero_inflate_logpmf,
        )

        eta = jnp.asarray(0.7)
        logit = jnp.asarray(0.4)
        pi = float(jax.nn.sigmoid(logit))
        base0 = float(jnp.exp(poisson_logpmf(jnp.asarray(0.0), eta)))
        got = float(
            jnp.exp(
                zero_inflate_logpmf(
                    jnp.asarray(0.0), poisson_logpmf(jnp.asarray(0.0), eta),
                    logit,
                )
            )
        )
        np.testing.assert_allclose(got, pi + (1 - pi) * base0, rtol=1e-6)

    def test_zip_map_recovers_truth(self):
        from pytensor_federated_tpu.models.countdata import (
            FederatedZeroInflPoissonGLM,
            generate_zi_count_data,
        )

        data, truth = generate_zi_count_data(
            8, n_obs=256, n_features=3, pi=0.35, seed=5
        )
        model = FederatedZeroInflPoissonGLM(data)
        m = model.find_map(num_steps=600)
        pi_hat = float(jax.nn.sigmoid(m["logit_pi"]))
        assert abs(pi_hat - truth["pi"]) < 0.08, pi_hat
        np.testing.assert_allclose(
            np.asarray(m["w"]), truth["w"], atol=0.15
        )
        # ZIP must out-fit plain Poisson on zero-inflated data
        from pytensor_federated_tpu.models.countdata import (
            FederatedPoissonGLM,
        )

        base = FederatedPoissonGLM(data)
        mb = base.find_map(num_steps=600)
        assert float(model.logp(m)) > float(base.logp(mb))

    def test_zinb_runs_and_predictive_zero_fraction(self):
        from pytensor_federated_tpu.models.countdata import (
            FederatedZeroInflNegBinGLM,
            generate_zi_count_data,
        )

        data, truth = generate_zi_count_data(
            4, n_obs=128, n_features=3, pi=0.4, dispersion=3.0, seed=9
        )
        model = FederatedZeroInflNegBinGLM(data)
        m = model.find_map(num_steps=500)
        assert np.isfinite(float(model.logp(m)))
        rep = model.predictive(m, jax.random.PRNGKey(0))
        (X, y), mask = model.data.tree()
        frac_rep = float(np.sum((np.asarray(rep) == 0) * np.asarray(mask))
                         / np.sum(np.asarray(mask)))
        frac_obs = float(np.sum((np.asarray(y) == 0) * np.asarray(mask))
                         / np.sum(np.asarray(mask)))
        assert abs(frac_rep - frac_obs) < 0.1, (frac_rep, frac_obs)

    def test_prior_predictive_plumbing(self):
        from pytensor_federated_tpu.models.countdata import (
            FederatedZeroInflPoissonGLM,
            generate_zi_count_data,
        )

        data, _ = generate_zi_count_data(4, n_obs=16, n_features=2)
        model = FederatedZeroInflPoissonGLM(data)
        p = model.sample_prior(jax.random.PRNGKey(1))
        assert "logit_pi" in p
        assert np.isfinite(float(model.logp(p)))
