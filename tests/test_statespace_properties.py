"""Property-based LGSSM tests (hypothesis).

The example-based suite (test_statespace.py) pins specific shapes; these
properties sweep the space the associative-scan construction must cover:
latent dims 1-3, observation dims 1-2, lengths from T=1 up, arbitrary
observation masks (including all-missing), and random stable dynamics —
asserting the parallel filter always agrees with the sequential golden
filter, in value and gradient.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from pytensor_federated_tpu.models.statespace import (
    kalman_logp_parallel,
    kalman_logp_seq,
)

# Each fresh (d, k, T) combination pays eager dispatch / trace cost, so
# the random sweep is small; the dimension corners the example-based
# suite doesn't reach (d=1, k=2, T=1) are pinned deterministically in
# test_dimension_corners below.
COMMON = settings(max_examples=5, deadline=None)


def _make_case(d, k, T, seed, mask_bits=None):
    rng = np.random.default_rng(seed)
    # Spectral-radius-bounded F keeps the filter well-conditioned.
    F = rng.normal(size=(d, d))
    F = 0.9 * F / max(1.0, np.max(np.abs(np.linalg.eigvals(F))))
    params = {
        "F": jnp.asarray(F, jnp.float32),
        "H": jnp.asarray(rng.normal(size=(k, d)), jnp.float32),
        "log_q": jnp.asarray(rng.uniform(-2.0, 0.0), jnp.float32),
        "log_r": jnp.asarray(rng.uniform(-2.0, 0.0), jnp.float32),
        "m0": jnp.asarray(rng.normal(size=d), jnp.float32),
    }
    y = jnp.asarray(rng.normal(size=(T, k)), jnp.float32)
    mask = None if mask_bits is None else jnp.asarray(mask_bits, jnp.float32)
    return params, y, mask


def _check_case(params, y, mask):
    """Value + gradient agreement, plus the all-masked degenerate case
    (fused into one check so each shape pays its trace cost once;
    jitted — compile+run is ~2x faster than eager dispatch for these
    graphs even with every example being a fresh shape)."""
    lp_seq, g_seq = jax.jit(
        jax.value_and_grad(lambda p: kalman_logp_seq(p, y, mask))
    )(params)
    lp_par, g_par = jax.jit(
        jax.value_and_grad(lambda p: kalman_logp_parallel(p, y, mask))
    )(params)
    lp_seq, lp_par = float(lp_seq), float(lp_par)
    assert np.isfinite(lp_seq)
    np.testing.assert_allclose(lp_par, lp_seq, rtol=2e-3, atol=1e-3)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(g_par[key]),
            np.asarray(g_seq[key]),
            rtol=5e-3,
            atol=5e-3,
            err_msg=key,
        )
    # With every observation missing there is no likelihood term.
    lp0 = float(
        kalman_logp_parallel(params, y, jnp.zeros(y.shape[0], jnp.float32))
    )
    np.testing.assert_allclose(lp0, 0.0, atol=1e-6)


@st.composite
def lgssm_cases(draw):
    d = draw(st.integers(1, 3))
    k = draw(st.integers(1, 2))
    T = draw(st.integers(1, 12))
    seed = draw(st.integers(0, 2**31 - 1))
    mask_bits = draw(
        st.one_of(
            st.none(),
            st.lists(st.sampled_from([0.0, 1.0]), min_size=T, max_size=T),
        )
    )
    return _make_case(d, k, T, seed, mask_bits)


@COMMON
@given(lgssm_cases())
def test_parallel_matches_sequential(case):
    _check_case(*case)


@pytest.mark.parametrize(
    "d,k,T,mask_bits",
    [
        (1, 1, 1, None),  # scalar everything, single step
        (1, 2, 4, [1.0, 0.0, 0.0, 1.0]),  # k > d, interior gap
        (3, 2, 12, None),  # largest dims
        (2, 1, 7, [0.0] + [1.0] * 6),  # masked first step (prior element)
    ],
)
def test_dimension_corners(d, k, T, mask_bits):
    _check_case(*_make_case(d, k, T, seed=42, mask_bits=mask_bits))
