"""Native C++ worker node + TCP npwire transport (native/cpp_node.cpp).

Proves the cross-language federation boundary the reference only claims
(reference: README.md:34-35 "the model implementation could be C++"):
a zero-Python C++ node serves logp+grad over the npwire protocol, and
the Python driver embeds it differentiably.  Pattern parity: localhost
child-process servers (reference: test_service.py:180-224), golden-model
equivalence against an in-language implementation (reference:
test_demo_node.py:29-65).

Requires g++ (skips otherwise); builds via make -C native.
"""

import math
import shutil
import struct
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None and not (NATIVE / "cpp_node").exists(),
    reason="no g++ and no prebuilt cpp_node",
)


@pytest.fixture(scope="module")
def cpp_node_bin():
    if shutil.which("make") and shutil.which("g++"):
        subprocess.run(
            ["make", "-C", str(NATIVE)], check=True, capture_output=True
        )
    binary = NATIVE / "cpp_node"
    assert binary.exists()
    return str(binary)


def _free_ports(n):
    """n distinct free ports; all probe sockets stay open until every
    port is collected, so the kernel can't hand back a duplicate."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


@pytest.fixture()
def cpp_node(cpp_node_bin):
    (port,) = _free_ports(1)
    proc = subprocess.Popen(
        [cpp_node_bin, str(port)],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        line = proc.stdout.readline()  # readiness barrier
        assert "listening" in line, line
        yield port
    finally:
        proc.kill()
        proc.wait()


def ref_logp_grad(a, b, sigma, x, y):
    """In-language ground truth for the node's model."""
    resid = y - (a + b * x)
    logp = np.sum(
        -0.5 * (resid / sigma) ** 2 - np.log(sigma) - 0.5 * math.log(2 * math.pi)
    )
    ga = np.sum(resid / sigma**2)
    gb = np.sum(resid / sigma**2 * x)
    return logp, ga, gb


class TestCppNode:
    def test_matches_python_ground_truth(self, cpp_node):
        from pytensor_federated_tpu.service import TcpArraysClient

        rng = np.random.default_rng(0)
        x = rng.normal(size=200)
        y = 1.5 + 2.0 * x + 0.5 * rng.normal(size=200)
        client = TcpArraysClient("127.0.0.1", cpp_node)
        out = client.evaluate(
            np.float64(0.7), np.float64(1.9), np.float64(0.5), x, y
        )
        assert len(out) == 3
        want = ref_logp_grad(0.7, 1.9, 0.5, x, y)
        for got, exp in zip(out, want):
            assert got.shape == ()
            np.testing.assert_allclose(float(got), exp, rtol=1e-12)
        client.close()

    def test_tenant_stamped_request_served(self, cpp_node):
        """The tenant block (npwire flag 32, ISSUE 12) must be
        framing-validated and dropped by the native node — a
        gateway-fronted C++ replica serves tenant-stamped frames
        identically to plain ones."""
        from pytensor_federated_tpu.service import TcpArraysClient

        rng = np.random.default_rng(7)
        x = rng.normal(size=32)
        y = 1.0 + 0.5 * x
        client = TcpArraysClient("127.0.0.1", cpp_node, tenant="acme/eu")
        out = client.evaluate(
            np.float64(1.0), np.float64(0.5), np.float64(1.0), x, y
        )
        want = ref_logp_grad(1.0, 0.5, 1.0, x, y)
        for got, exp in zip(out, want):
            np.testing.assert_allclose(float(got), exp, rtol=1e-12)
        # Pipelined + batch-framed windows keep working tenant-stamped.
        reqs = [
            (np.float64(0.1 * i), np.float64(0.5), np.float64(1.0), x, y)
            for i in range(6)
        ]
        res = client.evaluate_many(reqs, window=3)
        for i, out_i in enumerate(res):
            want_i, _, _ = ref_logp_grad(0.1 * i, 0.5, 1.0, x, y)
            np.testing.assert_allclose(float(out_i[0]), want_i, rtol=1e-12)
        client.close()

    def test_partition_sliced_reply(self, cpp_node):
        """The partition block (npwire flag 64, ISSUE 13): the native
        node serves the head/tail SLICED reply — [logp, slice of the
        flat (g_a, g_b) tail] with the block echoed — and refuses a
        geometry disagreement in-band, loudly."""
        from pytensor_federated_tpu.routing.partition import (
            GradPartition,
            Reassembler,
            plan_partitions,
        )
        from pytensor_federated_tpu.service import TcpArraysClient
        from pytensor_federated_tpu.service.tcp import RemoteComputeError

        rng = np.random.default_rng(3)
        x = rng.normal(size=64)
        y = 0.3 + 1.1 * x
        args = (np.float64(0.3), np.float64(1.1), np.float64(0.8), x, y)
        client = TcpArraysClient("127.0.0.1", cpp_node)
        full = client.evaluate(*args)
        # The tail = (g_a, g_b): 2 scalars, flat total 2.
        re = Reassembler(2, 2)
        for part in plan_partitions(2, 2):
            head, sl = client.evaluate(*args, partition=part)
            np.testing.assert_allclose(float(head), float(full[0]))
            re.add(part, np.asarray(sl))
        flat = re.result()
        np.testing.assert_allclose(flat[0], float(full[1]), rtol=1e-12)
        np.testing.assert_allclose(flat[1], float(full[2]), rtol=1e-12)
        # Geometry disagreement: loud in-band error, connection lives.
        with pytest.raises(RemoteComputeError, match="partition total"):
            client.evaluate(*args, partition=GradPartition(0, 1, 0, 9, 9))
        out = client.evaluate(*args)
        np.testing.assert_allclose(float(out[0]), float(full[0]))
        # A reduce window (outer partition on a batch frame) is
        # refused loudly — the native node serves slices only.
        with pytest.raises(RemoteComputeError, match="not supported"):
            client.evaluate_reduced(
                [args, args], window=2, slices=1, total=2
            )
        client.close()

    def test_many_lockstep_calls_one_connection(self, cpp_node):
        from pytensor_federated_tpu.service import TcpArraysClient

        rng = np.random.default_rng(1)
        x = rng.normal(size=64)
        y = 2.0 * x
        client = TcpArraysClient("127.0.0.1", cpp_node)
        for i in range(50):
            out = client.evaluate(
                np.float64(0.0), np.float64(i * 0.1), np.float64(1.0), x, y
            )
            want, _, _ = ref_logp_grad(0.0, i * 0.1, 1.0, x, y)
            np.testing.assert_allclose(float(out[0]), want, rtol=1e-12)
        client.close()

    def test_pipelined_batch_matches_sequential(self, cpp_node):
        """evaluate_many keeps `window` frames in flight on the same
        connection; results must equal per-call evaluation exactly."""
        from pytensor_federated_tpu.service import TcpArraysClient

        rng = np.random.default_rng(2)
        x = rng.normal(size=64)
        y = 2.0 * x
        client = TcpArraysClient("127.0.0.1", cpp_node)
        reqs = [
            (np.float64(0.0), np.float64(i * 0.1), np.float64(1.0), x, y)
            for i in range(21)
        ]
        batch = client.evaluate_many(reqs, window=6)
        assert len(batch) == 21
        for args, out in zip(reqs, batch):
            seq = client.evaluate(*args)
            for a, b in zip(seq, out):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert client.evaluate_many([]) == []
        client.close()

    def test_pipelined_midbatch_error_keeps_connection(self, cpp_node):
        """A bad-request error reply mid-batch raises, and the SAME
        connection still serves the next call (drain keeps the
        lock-step correlation)."""
        from pytensor_federated_tpu.service import (
            RemoteComputeError,
            TcpArraysClient,
        )

        rng = np.random.default_rng(3)
        x = rng.normal(size=8)
        y = 2.0 * x
        good = (np.float64(0.0), np.float64(2.0), np.float64(1.0), x, y)
        bad = (np.float64(0.0),)  # wrong arity -> error reply
        client = TcpArraysClient("127.0.0.1", cpp_node)
        with pytest.raises(RemoteComputeError):
            client.evaluate_many([good, bad, good, good], window=4)
        out = client.evaluate(*good)  # connection survived, correlated
        want, _, _ = ref_logp_grad(0.0, 2.0, 1.0, x, y)
        np.testing.assert_allclose(float(out[0]), want, rtol=1e-12)
        client.close()

    def test_batch_frames_negotiated_and_match_sequential(self, cpp_node):
        """The node answers the zero-item probe (capability yes) and a
        batched window — K requests in ONE wire frame — returns
        exactly the per-call results."""
        from pytensor_federated_tpu.service import TcpArraysClient

        rng = np.random.default_rng(7)
        x = rng.normal(size=32)
        y = 2.0 * x
        client = TcpArraysClient("127.0.0.1", cpp_node)
        assert client._probe_batch() is True
        reqs = [
            (np.float64(0.1), np.float64(i * 0.2), np.float64(1.0), x, y)
            for i in range(11)
        ]
        batched = client.evaluate_many(reqs, window=4, batch=True)
        plain = client.evaluate_many(reqs, window=4, batch=False)
        for b, p in zip(batched, plain):
            for ab, ap in zip(b, p):
                np.testing.assert_array_equal(
                    np.asarray(ab), np.asarray(ap)
                )
        client.close()

    def test_batch_poisoned_item_isolated_on_the_wire(self, cpp_node):
        """One wrong-arity item inside a batch frame fails only ITS
        reply slot; siblings carry real results (raw-frame check, so
        the per-item isolation is proven at the wire, not masked by
        the client's first-error raise)."""
        import socket as socket_mod

        from pytensor_federated_tpu.service.npwire import (
            decode_arrays_all,
            decode_batch,
            encode_arrays,
            encode_batch,
        )

        rng = np.random.default_rng(8)
        x = rng.normal(size=16)
        y = 2.0 * x
        args = [np.float64(0.0), np.float64(2.0), np.float64(1.0), x, y]
        good = encode_arrays([np.asarray(a) for a in args], uuid=b"g" * 16)
        bad = encode_arrays([np.zeros(2)], uuid=b"b" * 16)  # wrong arity
        frame = encode_batch([good, bad, good], uuid=b"o" * 16)
        with socket_mod.create_connection(("127.0.0.1", cpp_node)) as s:
            s.sendall(struct.pack("<I", len(frame)) + frame)
            hdr = b""
            while len(hdr) < 4:
                hdr += s.recv(4 - len(hdr))
            (rlen,) = struct.unpack("<I", hdr)
            reply = b""
            while len(reply) < rlen:
                reply += s.recv(min(65536, rlen - len(reply)))
        items, ruid, err, _tid, _sp = decode_batch(reply)
        assert ruid == b"o" * 16 and err is None and len(items) == 3
        out0, u0, e0, _, _ = decode_arrays_all(items[0])
        _o1, u1, e1, _, _ = decode_arrays_all(items[1])
        out2, _u2, e2, _, _ = decode_arrays_all(items[2])
        assert e0 is None and e2 is None
        assert e1 is not None and u1 == b"b" * 16
        want, _, _ = ref_logp_grad(0.0, 2.0, 1.0, x, y)
        np.testing.assert_allclose(float(out0[0]), want, rtol=1e-12)
        np.testing.assert_allclose(float(out2[0]), want, rtol=1e-12)

    def test_error_reply(self, cpp_node):
        from pytensor_federated_tpu.service import (
            RemoteComputeError,
            TcpArraysClient,
        )

        client = TcpArraysClient("127.0.0.1", cpp_node)
        with pytest.raises(RemoteComputeError, match="5 inputs"):
            client.evaluate(np.float64(1.0))
        # Connection stays usable after an error reply.
        out = client.evaluate(
            np.float64(0.0),
            np.float64(0.0),
            np.float64(1.0),
            np.zeros(4),
            np.zeros(4),
        )
        assert len(out) == 3
        client.close()

    def test_wrong_dtype_rejected(self, cpp_node):
        from pytensor_federated_tpu.service import (
            RemoteComputeError,
            TcpArraysClient,
        )

        client = TcpArraysClient("127.0.0.1", cpp_node)
        with pytest.raises(RemoteComputeError, match="float64"):
            client.evaluate(
                np.float32(0.0),
                np.float64(0.0),
                np.float64(1.0),
                np.zeros(4),
                np.zeros(4),
            )
        client.close()

    def test_differentiable_in_jax_graph(self, cpp_node):
        """The C++ node plugs into blackbox_logp_grad: jax.grad flows
        through the native process (CPU host-callback path)."""
        import jax
        import jax.numpy as jnp

        from pytensor_federated_tpu import blackbox_logp_grad
        from pytensor_federated_tpu.service import TcpArraysClient

        rng = np.random.default_rng(2)
        x = rng.normal(size=100)
        y = 1.0 + 2.0 * x + 0.3 * rng.normal(size=100)
        client = TcpArraysClient("127.0.0.1", cpp_node)

        def host_fn(a, b):
            lp, ga, gb = client.evaluate(
                np.asarray(a, np.float64),
                np.asarray(b, np.float64),
                np.float64(0.3),
                x,
                y,
            )
            return (
                np.float32(lp),
                [np.float32(ga), np.float32(gb)],
            )

        with jax.default_device(jax.devices("cpu")[0]):
            op = blackbox_logp_grad(
                host_fn,
                [
                    jax.ShapeDtypeStruct((), jnp.float32),
                    jax.ShapeDtypeStruct((), jnp.float32),
                ],
            )
            g = jax.grad(lambda ab: op.logp(ab[0], ab[1]))(
                jnp.array([1.0, 2.0], jnp.float32)
            )
        _, ga, gb = ref_logp_grad(1.0, 2.0, 0.3, x, y)
        np.testing.assert_allclose(np.asarray(g), [ga, gb], rtol=1e-4)
        client.close()


class TestCppNodePool:
    def test_multiport_pool_and_concurrent_clients(self, cpp_node_bin):
        """One process, several ports (the reference's worker pool,
        reference: demo_node.py:98-108, collapsed into threads), with
        concurrent clients hammering every port at once — every reply
        must carry the right numbers for its own request."""
        from pytensor_federated_tpu.service import TcpArraysClient

        ports = _free_ports(3)
        proc = subprocess.Popen(
            [cpp_node_bin] + [str(p) for p in ports],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            for _ in ports:  # one readiness line per port
                line = proc.stdout.readline()
                assert "listening" in line, line

            rng = np.random.default_rng(3)
            x = rng.normal(size=64)
            y = 2.0 * x
            errors = []

            def hammer(port, slope_base):
                try:
                    client = TcpArraysClient("127.0.0.1", port)
                    for i in range(20):
                        slope = slope_base + i * 0.01
                        out = client.evaluate(
                            np.float64(0.0),
                            np.float64(slope),
                            np.float64(1.0),
                            x,
                            y,
                        )
                        want, _, _ = ref_logp_grad(0.0, slope, 1.0, x, y)
                        np.testing.assert_allclose(
                            float(out[0]), want, rtol=1e-12
                        )
                    client.close()
                except Exception as e:  # surfaced after join
                    errors.append(e)

            threads = [
                threading.Thread(target=hammer, args=(p, 0.1 * j))
                for j, p in enumerate(ports)
                for _ in range(2)  # two concurrent clients per port
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors
        finally:
            proc.kill()
            proc.wait()


class TestCppNodeHostileFrames:
    def _send_raw(self, port, payload):
        import socket as socket_mod
        import struct

        with socket_mod.create_connection(("127.0.0.1", port), 5) as s:
            s.sendall(struct.pack("<I", len(payload)) + payload)
            s.settimeout(5)
            try:
                hdr = s.recv(4)
            except (ConnectionResetError, TimeoutError):
                return None
            if len(hdr) < 4:
                return None
            (n,) = struct.unpack("<I", hdr)
            buf = b""
            while len(buf) < n:
                chunk = s.recv(n - len(buf))
                if not chunk:
                    return None
                buf += chunk
            return buf

    def test_truncated_lengths_fail_loudly_not_crash(self, cpp_node):
        """Attacker-controlled length fields (err_len, dtype_len,
        n_arrays, data_len) far beyond the payload must produce a
        decode-error reply or a closed connection — never a crash or
        multi-GiB allocation — and the node must keep serving."""
        import struct

        import numpy as np  # noqa: F811 (clarity)

        from pytensor_federated_tpu.service import TcpArraysClient

        uuid = b"\x00" * 16
        base = b"NPW1" + bytes([1])  # magic + version
        hostile = [
            # flags=1, err_len=0xFFFFFFFF, no error bytes
            base + bytes([1]) + uuid + struct.pack("<I", 0)
            + struct.pack("<I", 0xFFFFFFFF),
            # n_arrays=0xFFFFFFFF (allocation bomb)
            base + bytes([0]) + uuid + struct.pack("<I", 0xFFFFFFFF),
            # one array, dtype_len=0xFFFF beyond payload
            base + bytes([0]) + uuid + struct.pack("<I", 1)
            + struct.pack("<H", 0xFFFF),
            # one array, valid dtype, data_len=2^62
            base + bytes([0]) + uuid + struct.pack("<I", 1)
            + struct.pack("<H", 3) + b"<f8" + bytes([0])
            + struct.pack("<Q", 1 << 62),
        ]
        for payload in hostile:
            reply = self._send_raw(cpp_node, payload)
            if reply is not None:  # error reply is fine; crash is not
                assert b"truncated" in reply or b"exceeds" in reply, reply

        # Hostile FRAME length prefix (the outermost allocation bomb):
        # the node must drop the connection without allocating 4 GiB.
        import socket as socket_mod

        with socket_mod.create_connection(
            ("127.0.0.1", cpp_node), 5
        ) as s:
            s.sendall(struct.pack("<I", 0xFFFFFFFF))
            s.settimeout(5)
            assert s.recv(4) == b""  # server closed the connection

        # The node survived all of it and still serves real requests.
        client = TcpArraysClient("127.0.0.1", cpp_node)
        out = client.evaluate(
            np.float64(0.0),
            np.float64(1.0),
            np.float64(1.0),
            np.zeros(4),
            np.zeros(4),
        )
        assert len(out) == 3
        client.close()


class TestPythonTcpServer:
    """The pure-Python peer (serve_tcp_once) speaks the same protocol."""

    def test_roundtrip_and_client_retry(self):
        from pytensor_federated_tpu.service import (
            TcpArraysClient,
            serve_tcp_once,
        )

        def double(*arrays):
            return [2.0 * a for a in arrays]

        port_box = {}
        ready = threading.Event()

        def ready_cb(port):
            port_box["port"] = port
            ready.set()

        t = threading.Thread(
            target=serve_tcp_once,
            args=(double,),
            kwargs={"ready_callback": ready_cb, "max_connections": 1},
            daemon=True,
        )
        t.start()
        assert ready.wait(10)
        client = TcpArraysClient("127.0.0.1", port_box["port"])
        out = client.evaluate(np.arange(5.0), np.float64(3.0))
        np.testing.assert_array_equal(out[0], 2.0 * np.arange(5.0))
        np.testing.assert_array_equal(out[1], 6.0)
        client.close()
        t.join(timeout=10)


class TestFaultPlanFlag:
    """--fault-plan: the cross-language slice of the chaos subsystem
    (faultinject.FaultPlan.native_spec emits the spec format)."""

    def _spawn(self, cpp_node_bin, spec):
        (port,) = _free_ports(1)
        proc = subprocess.Popen(
            [cpp_node_bin, str(port), "--fault-plan", spec],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        line = proc.stdout.readline()
        assert "listening" in line, line
        return proc, port

    def _args(self, slope=2.0):
        x = np.arange(8.0)
        return (
            np.float64(0.0), np.float64(slope), np.float64(1.0),
            x, 2.0 * x,
        )

    def test_delay_then_disconnect_then_truncate(self, cpp_node_bin):
        from pytensor_federated_tpu import faultinject as fi
        from pytensor_federated_tpu.service import TcpArraysClient

        plan = fi.FaultPlan(
            [
                fi.FaultRule("delay", nth=2, delay_s=0.25),
                fi.FaultRule("disconnect", nth=4),
                fi.FaultRule("truncate_frame", nth=6, cut_frac=0.5),
            ]
        )
        spec = plan.native_spec()
        assert spec == "delay:2:250,disconnect:4,truncate:6:50"
        proc, port = self._spawn(cpp_node_bin, spec)
        try:
            client = TcpArraysClient(
                "127.0.0.1", port, retries=0, connect_retries=2
            )
            want, _, _ = ref_logp_grad(0.0, 2.0, 1.0, np.arange(8.0),
                                       2.0 * np.arange(8.0))
            # frame 1: clean
            out = client.evaluate(*self._args())
            np.testing.assert_allclose(float(out[0]), want, rtol=1e-12)
            # frame 2: delayed but correct
            t0 = time.perf_counter()
            out = client.evaluate(*self._args())
            assert time.perf_counter() - t0 >= 0.25
            np.testing.assert_allclose(float(out[0]), want, rtol=1e-12)
            # frame 3: clean
            client.evaluate(*self._args())
            # frame 4: the node closes the connection without replying —
            # a LOUD transport error, and a retries=1 client recovers.
            with pytest.raises((ConnectionError, OSError)):
                client.evaluate(*self._args())
            client.close()
            client = TcpArraysClient("127.0.0.1", port, retries=0)
            # frame 5: clean on a fresh connection
            client.evaluate(*self._args())
            # frame 6: reply truncated MID-frame -> the framed read
            # fails loudly ("peer closed mid-frame"), never a silent
            # short frame.
            with pytest.raises((ConnectionError, OSError)):
                client.evaluate(*self._args())
            client.close()
            # frame 7: the plan is exhausted; service is healthy.
            client = TcpArraysClient("127.0.0.1", port, retries=0)
            out = client.evaluate(*self._args())
            np.testing.assert_allclose(float(out[0]), want, rtol=1e-12)
            client.close()
        finally:
            proc.kill()
            proc.wait()

    def test_spec_from_file(self, cpp_node_bin, tmp_path):
        from pytensor_federated_tpu.service import TcpArraysClient

        spec_file = tmp_path / "plan.txt"
        spec_file.write_text("disconnect:1\n")
        proc, port = self._spawn(cpp_node_bin, str(spec_file))
        try:
            client = TcpArraysClient("127.0.0.1", port, retries=0)
            with pytest.raises((ConnectionError, OSError)):
                client.evaluate(*self._args())
            client.close()
            client = TcpArraysClient("127.0.0.1", port, retries=0)
            out = client.evaluate(*self._args())
            assert len(out) == 3
            client.close()
        finally:
            proc.kill()
            proc.wait()

    def test_malformed_spec_exits_loudly(self, cpp_node_bin):
        (port,) = _free_ports(1)
        out = subprocess.run(
            [cpp_node_bin, str(port), "--fault-plan", "meteor:xyz"],
            capture_output=True,
            text=True,
            timeout=30,
        )
        assert out.returncode == 2
        assert "fault-plan" in out.stderr


def _roundtrip_raw_frame(port, frame):
    import socket as socket_mod
    import struct as struct_mod

    with socket_mod.create_connection(("127.0.0.1", port), 5) as s:
        s.sendall(struct_mod.pack("<I", len(frame)) + bytes(frame))
        s.settimeout(5)
        hdr = s.recv(4)
        assert len(hdr) == 4
        (n,) = struct_mod.unpack("<I", hdr)
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            assert chunk, "node closed mid-reply"
            buf += chunk
    return buf


def test_corrupt_flag_block_rejected_loudly(cpp_node):
    """Regression (graftlint wire-registry): ISSUE 16 saturated the
    flag byte (128 = VERSION), so the loud-failure posture now shows as
    a corrupt-block refusal — a flag claiming a block the frame does
    not carry must fail in-band, never mis-parse the bytes after it."""
    from pytensor_federated_tpu.service.npwire import (
        _FLAGS_OFF,
        decode_arrays,
        encode_arrays,
    )

    frame = bytearray(encode_arrays([]))
    frame[_FLAGS_OFF] |= 0x80  # VERSION flag with no version block
    _arrays, _uuid, error = decode_arrays(
        _roundtrip_raw_frame(cpp_node, frame)
    )
    assert error is not None and "truncated version block" in error


def test_versioned_request_refused_loudly(cpp_node):
    """The sharded-optimizer lane (flag 128, ISSUE 16) needs node-owned
    optimizer state; the native node has none and must refuse IN-BAND —
    a silent pass-through would look like an applied update."""
    from pytensor_federated_tpu.service.npwire import (
        decode_arrays,
        encode_arrays,
    )

    frame = encode_arrays([np.zeros(3, np.float64)], version=7)
    _arrays, _uuid, error = decode_arrays(
        _roundtrip_raw_frame(cpp_node, frame)
    )
    assert error is not None
    assert "versioned" in error and "not supported" in error
