"""Child process for tests/test_incident_e2e.py: a TCP node that WEDGES.

A real process boundary (same pattern as multihost_proc.py /
elastic_proc.py — a script FILE, not a heredoc: CLAUDE.md spawn
pitfall) serving the npwire TCP protocol.  Computes ``2*x`` normally;
the first request whose leading element is negative blocks forever —
the stand-in for the tunneled runtime's silent-wedge failure mode,
which is precisely what the driver-side watchdog must turn into an
incident bundle.

stdout protocol: ``PORT <n>`` once listening, ``WEDGING`` when the
poison request arrives.
"""

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pytensor_federated_tpu.service.tcp import serve_tcp_once  # noqa: E402


def compute(*arrays):
    x = np.asarray(arrays[0], dtype=np.float64)
    if x.ravel()[0] < 0:
        print("WEDGING", flush=True)
        time.sleep(3600)  # the silent hang; the parent SIGKILLs us
    return [2.0 * x]


def main() -> int:
    serve_tcp_once(
        compute,
        ready_callback=lambda port: print(f"PORT {port}", flush=True),
        max_connections=None,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
