"""Ordinal (cumulative-logit) regression: golden, ordering, inference."""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats

from pytensor_federated_tpu.models.ordinal import (
    FederatedOrdinalRegression,
    cumulative_logit_loglik,
    generate_ordinal_data,
)


def _probs(eta, kappa):
    cdf = scipy.stats.logistic.cdf(np.concatenate([kappa, [np.inf]]) - eta)
    cdf = np.concatenate([[0.0], cdf])
    return np.diff(cdf)


def test_loglik_matches_direct_probability():
    rng = np.random.default_rng(0)
    kappa = np.array([-1.0, 0.2, 1.3], dtype=np.float32)
    for _ in range(5):
        eta = float(rng.normal(0, 1.5))
        p = _probs(eta, kappa)
        for c in range(4):
            ours = float(
                cumulative_logit_loglik(
                    jnp.asarray([float(c)]),
                    jnp.asarray([eta]),
                    jnp.asarray(kappa),
                )[0]
            )
            np.testing.assert_allclose(ours, np.log(p[c]), rtol=2e-4)


def test_probabilities_normalize():
    kappa = jnp.asarray([-0.5, 0.7])
    eta = jnp.linspace(-3, 3, 7)
    ll = jnp.stack(
        [
            cumulative_logit_loglik(jnp.full(7, float(c)), eta, kappa)
            for c in range(3)
        ]
    )
    np.testing.assert_allclose(
        np.exp(np.asarray(ll)).sum(axis=0), 1.0, rtol=1e-5
    )


def test_cutpoints_always_ordered():
    data, _ = generate_ordinal_data(4, n_obs=32, n_categories=5)
    m = FederatedOrdinalRegression(data, n_categories=5)
    rng = np.random.default_rng(3)
    for _ in range(5):
        p = m.init_params()
        p = jax.tree_util.tree_map(
            lambda a: a + rng.normal(0, 2.0, np.shape(a)), p
        )
        kappa = np.asarray(m._kappa(p))
        assert np.all(np.diff(kappa) > 0)


def test_map_recovers_truth():
    data, truth = generate_ordinal_data(
        8, n_obs=128, n_features=3, n_categories=4, seed=5
    )
    m = FederatedOrdinalRegression(data, n_categories=4)
    est = m.find_map()
    np.testing.assert_allclose(np.asarray(est["w"]), truth["w"], atol=0.25)
    kappa_est = np.asarray(m._kappa(est))
    np.testing.assert_allclose(kappa_est, truth["kappa"], atol=0.35)


def test_nuts_converges():
    data, truth = generate_ordinal_data(
        4, n_obs=96, n_features=2, n_categories=3, seed=7
    )
    m = FederatedOrdinalRegression(data, n_categories=3)
    res = m.sample(
        key=jax.random.PRNGKey(2),
        num_warmup=300,
        num_samples=300,
        num_chains=2,
    )
    summ = res.summary()
    assert float(np.max(np.asarray(summ["rhat"]["w"]))) < 1.1
    w_mean = np.asarray(res.samples["w"]).mean(axis=(0, 1))
    np.testing.assert_allclose(w_mean, truth["w"], atol=0.25)


def test_predictive_and_pointwise_contracts():
    data, _ = generate_ordinal_data(4, n_obs=48, n_categories=4, seed=9)
    m = FederatedOrdinalRegression(data, n_categories=4)
    p0 = m.init_params()
    (X, y), mask = data.tree()
    sim = m.predictive(p0, jax.random.PRNGKey(0))
    assert sim.shape == y.shape
    s = np.asarray(sim)
    assert np.all((s >= 0) & (s <= 3))
    assert np.all(s[np.asarray(mask) == 0] == 0.0)
    ll = m.pointwise_loglik(p0)
    assert np.all(np.asarray(ll)[np.asarray(mask) == 1] < 0.0)
    assert np.all(np.asarray(ll)[np.asarray(mask) == 0] == 0.0)


def test_on_mesh(devices8):
    from pytensor_federated_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"shards": 8}, devices=devices8)
    data, _ = generate_ordinal_data(8, n_obs=32, n_categories=3, seed=11)
    m_mesh = FederatedOrdinalRegression(data, n_categories=3, mesh=mesh)
    m_local = FederatedOrdinalRegression(data, n_categories=3)
    p0 = m_local.init_params()
    np.testing.assert_allclose(
        float(m_mesh.logp(p0)), float(m_local.logp(p0)), rtol=5e-4
    )


def test_out_of_range_category_fails_loudly():
    data, _ = generate_ordinal_data(4, n_obs=32, n_categories=5, seed=13)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="n_categories"):
        FederatedOrdinalRegression(data, n_categories=4)


def test_negative_or_fractional_categories_fail_loudly():
    import pytest as _pytest
    from pytensor_federated_tpu.parallel.packing import ShardedData

    data, _ = generate_ordinal_data(4, n_obs=32, n_categories=4, seed=13)
    (X, y), mask = data.tree()
    with _pytest.raises(ValueError, match="0..n_categories-1"):
        FederatedOrdinalRegression(
            ShardedData(data=(X, y - 1.0), mask=mask), n_categories=4
        )
    with _pytest.raises(ValueError, match="integer-coded"):
        FederatedOrdinalRegression(
            ShardedData(data=(X, y + 0.5 * np.asarray(mask)), mask=mask),
            n_categories=5,
        )
