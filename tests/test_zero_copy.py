"""Tier B of ISSUE 9: scatter/gather npwire + sendmsg TCP paths.

Satellites pinned here:

- ``_send_frame`` no longer copies the payload to prepend its length —
  header and payload ride one ``sendmsg`` vector.  Frame integrity is
  regression-tested for small frames AND frames far beyond SO_SNDBUF
  (where ``sendmsg`` returns partial counts and the resend arithmetic
  must slice buffers by BYTES), plus vectors longer than the IOV_MAX
  chunk.
- layout normalization happens ONCE at encode entry: Fortran-ordered
  and sliced inputs round-trip byte-identically to their contiguous
  copies on BOTH codecs (npwire and npproto).
- ``encode_arrays_sg``'s buffer vector joins byte-identical to the
  contiguous encoder, and ``copy=False`` decode returns read-only
  views into the frame with zero payload copies.
"""

import socket
import struct
import threading

import numpy as np
import pytest

from pytensor_federated_tpu.service import npproto_codec
from pytensor_federated_tpu.service.npwire import (
    WIRE_BYTES_COPIED,
    decode_arrays,
    decode_arrays_all,
    encode_arrays,
    encode_arrays_sg,
    fast_uuid,
    sg_nbytes,
)
from pytensor_federated_tpu.service.tcp import (
    _IOV_CHUNK,
    _recv_frame,
    _send_frame,
    _send_frame_vec,
    _sendmsg_all,
)


def _recv_thread(sock, out):
    try:
        out.append(_recv_frame(sock))
    except Exception as e:  # surfaced by the asserting test thread
        out.append(e)


def _roundtrip_frame(payload_parts, nbytes=None):
    """Send one frame through a socketpair with a SMALL send buffer so
    partial sends genuinely happen; return the received frame."""
    a, b = socket.socketpair()
    try:
        # Shrink the send buffer as far as the kernel allows: the
        # >SO_SNDBUF case is the partial-send regression this guards.
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        out = []
        t = threading.Thread(target=_recv_thread, args=(b, out))
        t.start()
        if isinstance(payload_parts, bytes):
            _send_frame(a, payload_parts)
        else:
            _send_frame_vec(a, payload_parts, nbytes)
        t.join(timeout=30)
        assert not t.is_alive(), "receiver hung"
        (got,) = out
        if isinstance(got, Exception):
            raise got
        return got
    finally:
        a.close()
        b.close()


class TestSendmsgFrames:
    def test_small_frame_integrity(self):
        payload = b"tiny"
        assert _roundtrip_frame(payload) == payload

    def test_beyond_sndbuf_frame_integrity(self):
        # Far past the 4 KiB send buffer: many partial sendmsg returns.
        rng = np.random.default_rng(0)
        payload = rng.integers(0, 256, 3_000_000, np.uint8).tobytes()
        assert _roundtrip_frame(payload) == payload

    def test_vectored_frame_matches_joined(self):
        arrays = [
            np.arange(100_000, dtype=np.float64),
            np.arange(7, dtype=np.int32),
            np.asarray(np.float32(3.5)),
        ]
        uid = fast_uuid()
        parts = encode_arrays_sg(arrays, uuid=uid)
        joined = encode_arrays(arrays, uuid=uid)
        assert b"".join(parts) == joined
        got = _roundtrip_frame(parts, sg_nbytes(parts))
        assert got == joined
        outs, ruid, err = decode_arrays(got)
        assert ruid == uid and err is None
        for x, o in zip(arrays, outs):
            assert np.array_equal(x, o) and o.dtype == x.dtype

    def test_more_buffers_than_iov_chunk(self):
        parts = [bytes([i % 256]) * 3 for i in range(_IOV_CHUNK * 2 + 5)]
        a, b = socket.socketpair()
        try:
            out = []

            def read_all(n):
                buf = b""
                while len(buf) < n:
                    chunk = b.recv(n - len(buf))
                    assert chunk
                    buf += chunk
                out.append(buf)

            total = sum(len(p) for p in parts)
            t = threading.Thread(target=read_all, args=(total,))
            t.start()
            _sendmsg_all(a, parts)
            t.join(timeout=30)
            assert out[0] == b"".join(parts)
        finally:
            a.close()
            b.close()


class TestLayoutNormalization:
    """Satellite: non-contiguous inputs normalize once at encode entry
    and round-trip byte-identically on BOTH codecs."""

    CASES = [
        np.asfortranarray(np.arange(24, dtype=np.float64).reshape(4, 6)),
        np.arange(40, dtype=np.float32)[::2],  # strided slice
        np.arange(60, dtype=np.int64).reshape(5, 12)[1:4, 2:9],
        np.asfortranarray(
            np.arange(8, dtype=np.complex128).reshape(2, 4)
        ),
    ]

    @pytest.mark.parametrize("case", range(len(CASES)))
    def test_npwire_roundtrip(self, case):
        x = self.CASES[case]
        contig = np.ascontiguousarray(x)
        enc_view = encode_arrays([x], uuid=b"u" * 16)
        enc_contig = encode_arrays([contig], uuid=b"u" * 16)
        assert enc_view == enc_contig  # byte-identical frames
        (out,), _u, _e = decode_arrays(enc_view)
        assert np.array_equal(out, x) and out.dtype == x.dtype

    @pytest.mark.parametrize("case", range(len(CASES)))
    def test_npproto_roundtrip(self, case):
        x = self.CASES[case]
        enc = npproto_codec.encode_arrays_msg([x], uuid="u" * 16)
        arrays, _uuid = npproto_codec.decode_arrays_msg(enc)
        assert np.array_equal(arrays[0], x)
        assert arrays[0].dtype == x.dtype

    def test_sg_keeps_contiguous_inputs_as_views(self):
        """An already-contiguous array ships as a zero-copy view (no
        layout copy counted); a strided one pays exactly one."""
        layout = WIRE_BYTES_COPIED.labels(
            lane="npwire", stage="encode_layout"
        )
        contig = np.arange(1024, dtype=np.float64)
        before = layout.value
        parts = encode_arrays_sg([contig], uuid=b"u" * 16)
        assert layout.value == before
        views = [p for p in parts if isinstance(p, memoryview)]
        assert views and views[0].obj is contig
        strided = contig[::2]
        before = layout.value
        encode_arrays_sg([strided], uuid=b"u" * 16)
        assert layout.value - before == strided.nbytes


class TestDecodeViews:
    def test_copy_false_returns_readonly_views(self):
        x = np.arange(256, dtype=np.float64)
        frame = encode_arrays([x], uuid=b"u" * 16)
        (out,), _u, _e, _t, _s = decode_arrays_all(frame, copy=False)
        assert np.array_equal(out, x)
        assert not out.flags.writeable
        assert not out.flags.owndata  # a view into the frame

    def test_copy_true_is_owned_single_copy(self):
        counter = WIRE_BYTES_COPIED.labels(
            lane="npwire", stage="decode_copy"
        )
        x = np.arange(256, dtype=np.float64)
        frame = encode_arrays([x], uuid=b"u" * 16)
        before = counter.value
        (out,), _u, _e, _t, _s = decode_arrays_all(frame, copy=True)
        assert counter.value - before == x.nbytes  # ONE copy, not two
        assert out.flags.writeable
        out[0] = 1e9  # owned: mutation cannot touch the frame
        (again,), _u2, _e2 = decode_arrays(frame)
        assert again[0] == 0.0

    def test_copy_false_truncation_still_loud(self):
        from pytensor_federated_tpu.service.npwire import WireError

        x = np.arange(64, dtype=np.float64)
        frame = encode_arrays([x], uuid=b"u" * 16)
        with pytest.raises(WireError):
            decode_arrays_all(frame[:-8], copy=False)


class TestFastUuid:
    def test_unique_and_16_bytes(self):
        ids = {fast_uuid() for _ in range(10_000)}
        assert len(ids) == 10_000
        assert all(len(u) == 16 for u in ids)

    def test_thread_safety(self):
        out = []
        lock = threading.Lock()

        def mint():
            local = [fast_uuid() for _ in range(2_000)]
            with lock:
                out.extend(local)

        threads = [threading.Thread(target=mint) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(set(out)) == len(out)
