"""Execute demos/demo_pymc.py under the pytensor + pymc shims.

The reference's flagship workflow (PyMC model, federated likelihood,
find_MAP + NUTS — reference demo_model.py:15-45) runs here end-to-end
with the REAL demo module: model building through
``bridge.federated_potential``, the JAX-linker lowering via the
bridge's ``jax_funcify`` registrations, the host ``perform`` path, and
the ``main()`` driver with posterior assertions against the generating
truth (intercept 1.5, slope 2.0 — models/linear.py:44-45).

Shim caveat (see tests/pymc_shim.py): this proves OUR-side logic — the
demo's 124 previously-unexecuted lines now run under test — not
real-pymc compatibility.
"""

import numpy as np
import pytest

from pymc_shim import demo_pymc_under_shims
import pytensor_shim as pts


@pytest.fixture(scope="module")
def shims():
    with demo_pymc_under_shims() as ns:
        yield ns


def _unconstrained(model, *, intercept, offsets, slope, log_sigma):
    u = {
        "intercept": np.float32(intercept),
        "offsets": np.asarray(offsets, np.float32),
        "slope": np.float32(slope),
        "sigma": np.float32(log_sigma),  # unconstrained = log sigma
    }
    # keep only names the model actually has, in its own order
    names = {rv.name for rv in model.free_rvs}
    assert names == set(u)
    return u


class TestModelParity:
    def test_federated_matches_native_logp(self, shims):
        """The dtype-seam parity claim in demo_pymc's docstring: the
        federated Potential model and the natively built model are the
        SAME posterior (reference: test_demo_node.py:68-110 compares a
        federated model against a native one the same way)."""
        demo = shims.demo
        data, _ = demo.generate_node_data(4, n_obs=32, seed=7)
        fed = demo.build_model(data)
        native = demo.build_native_model(data)

        fed_logp = fed.logp_fn()
        native_logp = native.logp_fn()
        rng = np.random.default_rng(0)
        for _ in range(3):
            point = _unconstrained(
                fed,
                intercept=rng.normal(1.5, 0.3),
                offsets=rng.normal(0.0, 0.2, size=4),
                slope=rng.normal(2.0, 0.3),
                log_sigma=rng.normal(-0.5, 0.2),
            )
            a = float(fed_logp(point))
            b = float(native_logp(point))
            assert np.isfinite(a) and np.isfinite(b)
            # f32 evaluation over ~128 observations: 1e-4 relative
            # (demo docstring pins ~1e-5 at float64-vs-float32 seam;
            # here BOTH sides are f32 so the gap is summation order).
            assert abs(a - b) <= 1e-4 * max(1.0, abs(a)), (a, b)

    def test_perform_path_matches_jax_path(self, shims):
        """build_model(use_jax_fn=False) routes the same likelihood
        through the host callable + op.perform (the C/py-linker path);
        both paths must agree numerically."""
        demo = shims.demo
        data, _ = demo.generate_node_data(4, n_obs=32, seed=7)
        host_model = demo.build_model(data, use_jax_fn=False)
        jax_model = demo.build_model(data, use_jax_fn=True)

        point = dict(
            intercept=np.float32(1.4),
            offsets=np.zeros(4, np.float32),
            slope=np.float32(2.1),
            sigma=np.float32(0.6),
        )
        # host path: evaluate the recorded Potential graph via perform
        (pot_host,) = pts.eval_graph(
            [host_model.potentials[0]],
            {rv.var: point[rv.name] for rv in host_model.free_rvs},
        )
        # jax path: full potential through the jax_funcify lowering
        jax_logp = jax_model.logp_fn()
        # isolate the potential on the jax side by rebuilding with the
        # same point through the compiled graph parts
        parts_fn = jax_model._compiled_graph_parts()
        (pot_jax,) = parts_fn(
            *[point[rv.name] for rv in jax_model.free_rvs]
        )
        np.testing.assert_allclose(
            np.asarray(pot_host), np.asarray(pot_jax), rtol=1e-5
        )
        assert np.isfinite(float(jax_logp(
            _unconstrained(
                jax_model,
                intercept=1.4,
                offsets=np.zeros(4),
                slope=2.1,
                log_sigma=np.log(0.6),
            )
        )))


class TestDriver:
    def test_main_end_to_end(self, shims):
        """The full driver: generate data, build the federated model,
        find_MAP, NUTS — posterior must recover the generating truth
        (slope 2.0, intercept 1.5)."""
        idata = shims.demo.main(
            [
                "--n-shards", "4",
                "--n-obs", "48",
                "--draws", "200",
                "--tune", "200",
                "--chains", "2",
            ]
        )
        post = idata.posterior
        slope = float(post["slope"].median())
        intercept = float(post["intercept"].median())
        sigma = float(post["sigma"].median())
        assert abs(slope - 2.0) < 0.15, slope
        assert abs(intercept - 1.5) < 0.4, intercept
        assert 0.3 < sigma < 0.8, sigma

    def test_find_map_recovers_truth(self, shims):
        demo = shims.demo
        data, _ = demo.generate_node_data(6, n_obs=64, seed=3)
        model = demo.build_model(data)
        with model:
            import pymc as pm  # the shim, installed by the fixture

            map_est = pm.find_MAP(progressbar=False)
        assert abs(map_est["slope"] - 2.0) < 0.1, map_est
        assert abs(map_est["intercept"] - 1.5) < 0.4, map_est
        assert 0.3 < map_est["sigma"] < 0.8, map_est
