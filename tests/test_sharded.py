"""Sharded evaluator tests: the psum hot path and heterogeneous packing.

Golden-model pattern from the reference: federated/sharded results must
match a natively-built single-device model exactly
(reference: test_demo_node.py:29-65).
"""

import jax

from pytensor_federated_tpu._compat import enable_x64
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu import FederatedLogp, pack_shards, sharded_compute
from pytensor_federated_tpu.parallel import make_mesh


def normal_loglik(params, shard):
    """Per-shard N(y | a + b*x, 1) log-likelihood with padding mask."""
    (x, y), mask = shard
    a, b = params["a"], params["b"]
    resid = y - (a + b * x)
    ll = -0.5 * resid**2 - 0.5 * jnp.log(2 * jnp.pi)
    return jnp.sum(ll * mask)


def make_data(n_shards=8, n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n_shards, n)).astype(np.float32)
    y = (1.5 + 2.0 * x + rng.normal(size=x.shape) * 0.1).astype(np.float32)
    mask = np.ones((n_shards, n), dtype=np.float32)
    return ((jnp.asarray(x), jnp.asarray(y)), jnp.asarray(mask))


def reference_logp(data, params):
    """Single-device ground truth (no sharding machinery)."""
    (x, y), mask = data
    resid = y - (params["a"] + params["b"] * x)
    ll = -0.5 * resid**2 - 0.5 * jnp.log(2 * jnp.pi)
    return jnp.sum(ll * mask)


PARAMS = {"a": jnp.float32(1.0), "b": jnp.float32(2.0)}


def test_federated_logp_single_device_matches_native():
    data = make_data()
    fed = FederatedLogp(normal_loglik, data)
    np.testing.assert_allclose(
        fed.logp(PARAMS), reference_logp(data, PARAMS), rtol=1e-5
    )


def test_federated_logp_grad_matches_native():
    data = make_data()
    fed = FederatedLogp(normal_loglik, data)
    v, g = fed.logp_and_grad(PARAMS)
    v_ref, g_ref = jax.value_and_grad(lambda p: reference_logp(data, p))(PARAMS)
    np.testing.assert_allclose(v, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g["a"], g_ref["a"], rtol=1e-5)
    np.testing.assert_allclose(g["b"], g_ref["b"], rtol=1e-5)


def test_federated_logp_on_mesh_matches_native(mesh8):
    data = make_data()
    fed = FederatedLogp(normal_loglik, data, mesh=mesh8)
    v, g = fed.logp_and_grad(PARAMS)
    v_ref, g_ref = jax.value_and_grad(lambda p: reference_logp(data, p))(PARAMS)
    np.testing.assert_allclose(v, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g["a"], g_ref["a"], rtol=1e-5)
    np.testing.assert_allclose(g["b"], g_ref["b"], rtol=1e-5)


def test_federated_logp_more_shards_than_devices(mesh8):
    data = make_data(n_shards=16)
    fed = FederatedLogp(normal_loglik, data, mesh=mesh8)
    np.testing.assert_allclose(
        fed.logp(PARAMS), reference_logp(data, PARAMS), rtol=1e-5
    )


def test_federated_logp_indivisible_shards_raises(mesh8):
    data = make_data(n_shards=6)
    with pytest.raises(ValueError, match="divisible"):
        FederatedLogp(normal_loglik, data, mesh=mesh8)


def test_per_shard_logps(mesh8):
    data = make_data()
    fed = FederatedLogp(normal_loglik, data, mesh=mesh8)
    per = fed.per_shard_logps(PARAMS)
    assert per.shape == (8,)
    np.testing.assert_allclose(jnp.sum(per), fed.logp(PARAMS), rtol=1e-5)


def test_pack_shards_heterogeneous():
    """Each 'node' owns a different-sized private dataset
    (reference: demo_node.py:58-61) — padded+masked logp must equal the
    unpadded sum."""
    rng = np.random.default_rng(42)
    shards = []
    for n in (5, 9, 3, 7):
        x = rng.normal(size=n).astype(np.float32)
        y = (1.0 + 2.0 * x).astype(np.float32)
        shards.append((x, y))
    packed = pack_shards(shards, pad_to_multiple=8)
    assert packed.n_shards == 4
    assert packed.max_len == 16
    fed = FederatedLogp(normal_loglik, packed.tree())
    expected = sum(
        float(
            reference_logp(
                ((jnp.asarray(x), jnp.asarray(y)), jnp.ones(len(x))), PARAMS
            )
        )
        for x, y in shards
    )
    np.testing.assert_allclose(float(fed.logp(PARAMS)), expected, rtol=1e-5)


def test_pack_shards_validates():
    with pytest.raises(ValueError, match="at least one"):
        pack_shards([])


def test_sharded_compute_generic(mesh8):
    """Generic arrays->arrays over shards (ArraysToArraysService analog)."""
    data = jnp.arange(8.0 * 4).reshape(8, 4)

    def per_shard(params, row):
        return {"scaled": params * row, "sum": jnp.sum(row)}

    fn = sharded_compute(per_shard, data, mesh=mesh8)
    out = fn(jnp.float32(2.0))
    np.testing.assert_allclose(out["scaled"], 2.0 * data)
    np.testing.assert_allclose(out["sum"], jnp.sum(data, axis=1))


def test_second_order_through_federated_boundary(mesh8):
    """jax.hessian differentiates straight through vmap/shard_map/psum —
    the capability the reference's boundary forbids (reference:
    wrapper_ops.py:123-125 rejects grads of its grad outputs)."""
    data = (jnp.arange(8.0).reshape(8, 1),)

    def per_shard(p, d):
        return -jnp.sum((d[0] - p["mu"]) ** 2) * p["scale"]

    p = {"mu": jnp.asarray(0.5), "scale": jnp.asarray(1.2)}
    single = FederatedLogp(per_shard, data)
    h1 = jax.hessian(single.logp)(p)
    # d2/dmu2 = -2 * n * scale
    np.testing.assert_allclose(float(h1["mu"]["mu"]), -2 * 8 * 1.2, rtol=1e-5)
    # mixed partial d2/dmu dscale = -2 * sum(mu - x)
    np.testing.assert_allclose(
        float(h1["mu"]["scale"]), float(-2 * jnp.sum(0.5 - jnp.arange(8.0))),
        rtol=1e-5,
    )
    on_mesh = FederatedLogp(per_shard, data, mesh=mesh8)
    h2 = jax.hessian(on_mesh.logp)(p)
    for k1 in h1:
        for k2 in h1[k1]:
            np.testing.assert_allclose(
                float(h2[k1][k2]), float(h1[k1][k2]), rtol=1e-5
            )


def test_forward_supplied_grads_keep_one_order_contract():
    """LogpGradOp (forward-supplied VJP) preserves the reference's
    no-second-order contract: hessian attempts fail loudly rather than
    silently returning wrong curvature."""
    from pytensor_federated_tpu.ops.ops import LogpGradOp

    op = LogpGradOp(lambda a: (-(a**2), (-2 * a,)))
    with pytest.raises(TypeError, match="custom_vjp"):
        jax.hessian(lambda a: op.logp(a))(jnp.asarray(2.0))


def test_remat_equivalence(mesh8):
    """remat=True recomputes activations in the backward pass without
    changing values or gradients."""
    data = (jnp.arange(16.0).reshape(8, 2),)

    def per_shard(p, d):
        return -jnp.sum(jnp.tanh((d[0] - p) ** 2))

    p = jnp.asarray(0.3)
    plain = FederatedLogp(per_shard, data, mesh=mesh8)
    remat = FederatedLogp(per_shard, data, mesh=mesh8, remat=True)
    v1, g1 = plain.logp_and_grad(p)
    v2, g2 = remat.logp_and_grad(p)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
    np.testing.assert_allclose(float(g1), float(g2), rtol=1e-6)


def test_x64_opt_in():
    """Exchange-dtype policy: float32 native by default (TPU-first),
    float64 via jax's own x64 switch — the explicit decision SURVEY §5
    calls for (the reference's de-facto wire dtype is float64)."""
    data = (jnp.arange(8.0).reshape(8, 1),)

    def per_shard(p, d):
        return -jnp.sum((d[0] - p) ** 2)

    fed32 = FederatedLogp(per_shard, data)
    assert fed32.logp(jnp.asarray(0.5)).dtype == jnp.float32
    with enable_x64():
        data64 = (jnp.arange(8.0, dtype=jnp.float64).reshape(8, 1),)
        fed64 = FederatedLogp(per_shard, data64)
        out = fed64.logp(jnp.asarray(0.5, dtype=jnp.float64))
        assert out.dtype == jnp.float64
        np.testing.assert_allclose(
            float(out), float(fed32.logp(jnp.asarray(0.5))), rtol=1e-6
        )


def test_logp_batch_matches_loop(mesh8):
    """Batched parameter evaluation (the many-concurrent-clients analog,
    reference: test_service.py:180-224) equals one-at-a-time evals."""
    data = (jnp.arange(16.0).reshape(8, 2),)

    def per_shard(p, d):
        return -jnp.sum((d[0] - p["mu"]) ** 2) * p["s"]

    batch = {
        "mu": jnp.linspace(-1.0, 1.0, 5),
        "s": jnp.linspace(0.5, 1.5, 5),
    }
    for mesh in (None, mesh8):
        fed = FederatedLogp(per_shard, data, mesh=mesh)
        got = fed.logp_batch(batch)
        assert got.shape == (5,)
        for i in range(5):
            p = jax.tree_util.tree_map(lambda l: l[i], batch)
            np.testing.assert_allclose(
                float(got[i]), float(fed.logp(p)), rtol=1e-5
            )
