"""The effect-handler front end (ISSUE 15): handlers, distributions,
and the plate→``fed_map`` compiler.

Covers the handler-composition edge cases the issue names — nested
plates, condition-vs-substitute precedence, subsample-scaling
unbiasedness (an exact enumeration plus a hypothesis property test),
and seeded-trace determinism across mesh/pool/mixed placements — and
pins the compiled-vs-direct logp+grad parity contract on every lane.
"""

import itertools
import math
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.stats

from pytensor_federated_tpu import fed, ppl
from pytensor_federated_tpu.ppl import PPLError
from pytensor_federated_tpu.ppl.distributions import (
    Bernoulli,
    Exponential,
    HalfNormal,
    HalfNormalLog,
    Normal,
)


def tiny_model(x):
    w = ppl.sample("w", Normal(0.0, 1.0))
    with ppl.plate("shards", x.shape[0]) as sh:
        b = ppl.sample("b", Normal(0.0, 1.0))
        xs = ppl.subsample(x, sh)
        ppl.sample("obs", Normal(w + b[:, None], 1.0), obs=xs)


@pytest.fixture(scope="module")
def tiny_data():
    return jnp.asarray(
        np.arange(12.0, dtype=np.float32).reshape(4, 3)
    )


@pytest.fixture(scope="module")
def tiny_params(tiny_data):
    c = ppl.compile(tiny_model, (tiny_data,))
    return c.sample_prior(jax.random.PRNGKey(1))


# ---------------------------------------------------------------------------
# distributions
# ---------------------------------------------------------------------------


class TestDistributions:
    def test_normal_matches_scipy(self):
        x = np.linspace(-3, 3, 7)
        got = np.asarray(Normal(0.5, 2.0).log_prob(jnp.asarray(x)))
        want = scipy.stats.norm.logpdf(x, 0.5, 2.0)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_halfnormal_matches_scipy(self):
        x = np.linspace(0.1, 4.0, 7)
        got = np.asarray(HalfNormal(1.5).log_prob(jnp.asarray(x)))
        want = scipy.stats.halfnorm.logpdf(x, scale=1.5)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_halfnormal_log_change_of_variables(self):
        # density of u = log x is halfnorm.pdf(e^u) * e^u
        u = np.linspace(-2.0, 1.0, 7)
        got = np.asarray(HalfNormalLog(1.0).log_prob(jnp.asarray(u)))
        want = scipy.stats.halfnorm.logpdf(np.exp(u)) + u
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_exponential_matches_scipy(self):
        x = np.linspace(0.1, 5.0, 7)
        got = np.asarray(Exponential(0.7).log_prob(jnp.asarray(x)))
        want = scipy.stats.expon.logpdf(x, scale=1 / 0.7)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_bernoulli_matches_scipy(self):
        logits = 0.8
        p = 1 / (1 + math.exp(-logits))
        for y in (0.0, 1.0):
            got = float(Bernoulli(logits).log_prob(y))
            want = scipy.stats.bernoulli.logpmf(int(y), p)
            np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_sample_shapes(self):
        key = jax.random.PRNGKey(0)
        assert Normal(0.0, 1.0).sample(key, (5,)).shape == (5,)
        assert Normal(jnp.zeros(3), 1.0).sample(key, (5,)).shape == (5, 3)
        assert HalfNormal(1.0).sample(key, (4,)).shape == (4,)
        assert float(jnp.min(HalfNormal(1.0).sample(key, (100,)))) > 0


# ---------------------------------------------------------------------------
# handlers
# ---------------------------------------------------------------------------


class TestHandlers:
    def test_sample_outside_handlers_is_loud(self):
        with pytest.raises(PPLError, match="outside any handler"):
            ppl.sample("w", Normal())

    def test_trace_records_in_order(self, tiny_data):
        tr = ppl.trace(
            ppl.seed(tiny_model, rng_key=jax.random.PRNGKey(0))
        ).get_trace(tiny_data)
        assert list(tr) == ["w", "b", "obs"]
        assert tr["obs"]["observed"] and not tr["w"]["observed"]
        assert tr["b"]["value"].shape == (4,)

    def test_duplicate_site_is_loud(self):
        def bad():
            ppl.sample("w", Normal())
            ppl.sample("w", Normal())

        with pytest.raises(PPLError, match="duplicate site"):
            ppl.trace(
                ppl.seed(bad, rng_key=jax.random.PRNGKey(0))
            ).get_trace()

    def test_seeded_trace_determinism(self, tiny_data):
        def draw(key):
            tr = ppl.trace(
                ppl.seed(tiny_model, rng_key=key)
            ).get_trace(tiny_data)
            return {k: np.asarray(v["value"]) for k, v in tr.items()}

        a = draw(jax.random.PRNGKey(7))
        b = draw(jax.random.PRNGKey(7))
        c = draw(jax.random.PRNGKey(8))
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
        assert not np.allclose(a["w"], c["w"])

    def test_replay_reproduces_draws(self, tiny_data):
        guide = ppl.trace(
            ppl.seed(tiny_model, rng_key=jax.random.PRNGKey(3))
        ).get_trace(tiny_data)
        replayed = ppl.trace(
            ppl.replay(
                ppl.seed(tiny_model, rng_key=jax.random.PRNGKey(99)),
                guide_trace=guide,
            )
        ).get_trace(tiny_data)
        np.testing.assert_array_equal(
            np.asarray(replayed["b"]["value"]),
            np.asarray(guide["b"]["value"]),
        )

    def test_condition_marks_observed_substitute_does_not(self):
        def m():
            ppl.sample("z", Normal())

        tr = ppl.trace(ppl.condition(m, data={"z": 1.5})).get_trace()
        assert tr["z"]["observed"] and float(tr["z"]["value"]) == 1.5
        tr = ppl.trace(ppl.substitute(m, data={"z": 2.5})).get_trace()
        assert not tr["z"]["observed"]
        assert float(tr["z"]["value"]) == 2.5

    def test_condition_vs_substitute_innermost_wins(self):
        """Precedence is purely positional: the INNER handler takes
        the site, whichever kind it is."""

        def m():
            ppl.sample("z", Normal())

        # substitute nested inside condition -> substitute wins
        tr = ppl.trace(
            ppl.condition(
                ppl.substitute(m, data={"z": 2.0}), data={"z": 1.0}
            )
        ).get_trace()
        assert float(tr["z"]["value"]) == 2.0
        assert not tr["z"]["observed"]
        # condition nested inside substitute -> condition wins
        tr = ppl.trace(
            ppl.substitute(
                ppl.condition(m, data={"z": 1.0}), data={"z": 2.0}
            )
        ).get_trace()
        assert float(tr["z"]["value"]) == 1.0
        assert tr["z"]["observed"]

    def test_obs_beats_every_handler(self):
        def m():
            ppl.sample("z", Normal(), obs=7.0)

        tr = ppl.trace(ppl.substitute(m, data={"z": 1.0})).get_trace()
        assert float(tr["z"]["value"]) == 7.0
        assert tr["z"]["observed"]

    def test_block_hides_from_outer_trace(self, tiny_data):
        inner = ppl.seed(tiny_model, rng_key=jax.random.PRNGKey(0))
        tr = ppl.trace(ppl.block(inner, hide=["b"])).get_trace(tiny_data)
        assert "b" not in tr and "w" in tr
        tr = ppl.trace(ppl.block(inner)).get_trace(tiny_data)
        assert not tr  # everything hidden

    def test_missing_latent_is_loud(self, tiny_data):
        with pytest.raises(PPLError, match="'b'"):
            ppl.log_density(
                tiny_model, (tiny_data,), {"w": jnp.zeros(())}
            )

    def test_nested_plates(self):
        def m(y):
            with ppl.plate("outer", 3):
                with ppl.plate("inner", 2):
                    z = ppl.sample("z", Normal())
                    ppl.sample("obs", Normal(z, 1.0), obs=y)

        y = jnp.zeros((3, 2))
        tr = ppl.trace(
            ppl.seed(m, rng_key=jax.random.PRNGKey(0))
        ).get_trace(y)
        # nested draws stack the plate axes outermost-first
        assert tr["z"]["value"].shape == (3, 2)
        frames = [f.name for f in tr["z"]["plates"]]
        assert frames == ["outer", "inner"]
        # and the density matches the hand-written sum
        params = {"z": tr["z"]["value"]}
        lp = ppl.log_density(m, (y,), params)
        want = np.sum(
            scipy.stats.norm.logpdf(np.asarray(params["z"]))
        ) + np.sum(
            scipy.stats.norm.logpdf(
                np.asarray(y), np.asarray(params["z"]), 1.0
            )
        )
        np.testing.assert_allclose(float(lp), want, rtol=1e-5)

    def test_subsample_outside_plate_is_loud(self):
        def m(x):
            ppl.subsample(x)

        with pytest.raises(PPLError, match="outside any active plate"):
            ppl.trace(m).get_trace(jnp.zeros((3,)))

    def test_plate_subsample_scales_and_slices(self):
        """An author-declared subsample_size draws indices under seed,
        slices data through subsample(), and scales site terms."""

        def m(y):
            with ppl.plate("n", 6, subsample_size=2) as p:
                ys = ppl.subsample(y, p)
                ppl.sample("obs", Normal(0.0, 1.0), obs=ys)

        y = jnp.asarray(np.arange(6.0, dtype=np.float32))
        tr = ppl.trace(
            ppl.seed(m, rng_key=jax.random.PRNGKey(0))
        ).get_trace(y)
        site = tr["obs"]
        assert site["value"].shape == (2,)
        assert site["scale"] == pytest.approx(3.0)
        assert site["plates"][0].effective == 2


# ---------------------------------------------------------------------------
# compiler: parity + unbiasedness
# ---------------------------------------------------------------------------


class TestCompile:
    def test_logp_matches_direct(self, tiny_data, tiny_params):
        c = ppl.compile(tiny_model, (tiny_data,))
        direct = ppl.log_density(tiny_model, (tiny_data,), tiny_params)
        np.testing.assert_allclose(
            float(c.logp(tiny_params)), float(direct), rtol=1e-6
        )

    def test_grad_matches_direct(self, tiny_data, tiny_params):
        c = ppl.compile(tiny_model, (tiny_data,))
        v, g = c.logp_and_grad(tiny_params)
        vd, gd = jax.value_and_grad(
            lambda p: ppl.log_density(tiny_model, (tiny_data,), p)
        )(tiny_params)
        np.testing.assert_allclose(float(v), float(vd), rtol=1e-6)
        for k in gd:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(gd[k]),
                rtol=1e-5, atol=1e-6,
            )

    def test_full_index_batch_equals_logp(self, tiny_data, tiny_params):
        c = ppl.compile(tiny_model, (tiny_data,))
        np.testing.assert_allclose(
            float(c.logp_indices(tiny_params, jnp.arange(4))),
            float(c.logp(tiny_params)),
            rtol=1e-6,
        )

    def test_subsample_unbiasedness_exact(self, tiny_data, tiny_params):
        """E over ALL (S choose m) index sets of the scaled minibatch
        logp == the full-data logp, exactly (a linear identity)."""
        c = ppl.compile(tiny_model, (tiny_data,))
        full = float(c.logp(tiny_params))
        for m in (1, 2, 3):
            vals = [
                float(c.logp_indices(tiny_params, jnp.asarray(idx)))
                for idx in itertools.combinations(range(4), m)
            ]
            np.testing.assert_allclose(np.mean(vals), full, rtol=1e-5)

    def test_minibatch_draws_without_replacement(
        self, tiny_data, tiny_params
    ):
        c = ppl.compile(tiny_model, (tiny_data,))
        v = c.logp_minibatch(
            tiny_params, jax.random.PRNGKey(0), batch_size=4
        )
        # batch == plate -> scale 1 -> exactly the full logp
        np.testing.assert_allclose(
            float(v), float(c.logp(tiny_params)), rtol=1e-6
        )

    def test_no_plate_is_loud(self):
        def m():
            ppl.sample("z", Normal())

        with pytest.raises(PPLError, match="outermost plate"):
            ppl.compile(m, ())

    def test_params_structure_mismatch_is_loud(
        self, tiny_data, tiny_params
    ):
        c = ppl.compile(tiny_model, (tiny_data,))
        with pytest.raises(PPLError, match="structure mismatch"):
            c.logp({"w": jnp.zeros(())})

    def test_nested_plate_model_compiles_on_outer(self):
        def m(y):
            w = ppl.sample("w", Normal())
            with ppl.plate("outer", 4) as po:
                ys = ppl.subsample(y, po)
                with ppl.plate("inner", 2):
                    z = ppl.sample("z", Normal())
                    ppl.sample("obs", Normal(w + z, 1.0), obs=ys)

        y = jnp.asarray(
            np.arange(8.0, dtype=np.float32).reshape(4, 2)
        )
        c = ppl.compile(m, (y,))
        assert c.plate_name == "outer" and c.n_shards == 4
        # inner-plate latent is GLOBAL w.r.t. the outer shard axis?
        # no: z sits inside outer too -> z is (4, 2) local
        p = c.sample_prior(jax.random.PRNGKey(0))
        assert p["z"].shape == (4, 2)
        direct = ppl.log_density(m, (y,), p)
        np.testing.assert_allclose(
            float(c.logp(p)), float(direct), rtol=1e-6
        )

    def test_condition_attached_data_compiles_correctly(self, tiny_data):
        """Review regression: data attached via ``condition`` (never
        passing through ``subsample``) carries the FULL plate axis
        into the per-shard lane — the plate must gather it, not let
        broadcasting silently count the whole dataset once per
        shard."""

        def latent_model(x):
            w = ppl.sample("w", Normal(0.0, 1.0))
            with ppl.plate("shards", 4):
                b = ppl.sample("b", Normal(0.0, 1.0))
                ppl.sample("obs", Normal(w + b[:, None], 1.0))

        conditioned = ppl.condition(
            latent_model, data={"obs": tiny_data}
        )
        c = ppl.compile(conditioned, (tiny_data,))
        p = {"w": jnp.asarray(0.3), "b": jnp.ones((4,))}
        direct = ppl.log_density(conditioned, (tiny_data,), p)
        np.testing.assert_allclose(
            float(c.logp(p)), float(direct), rtol=1e-6
        )

    def test_wrong_size_plate_value_is_loud(self, tiny_data):
        """A plate-scoped value matching neither the effective nor the
        full plate size refuses instead of broadcasting."""

        def bad_model(x):
            w = ppl.sample("w", Normal(0.0, 1.0))
            with ppl.plate("shards", 4):
                ppl.sample(
                    "obs", Normal(w, 1.0), obs=x[:2]
                )  # leading dim 2: neither 1 (shard) nor 4 (full)

        c_err = None
        try:
            ppl.compile(bad_model, (tiny_data,)).logp(
                {"w": jnp.zeros(())}
            )
        except PPLError as e:
            c_err = str(e)
        assert c_err is not None and "leading dim 2" in c_err

    def test_permuted_full_length_indices_stay_aligned(self, tiny_data):
        """Review regression: under a FULL-LENGTH permuted index set,
        latents must still be gathered (an already-the-right-size
        pass-through would pair shard i's latent with shard j's
        data)."""
        params = {
            "w": jnp.asarray(0.2),
            "b": jnp.asarray([0.0, 1.0, 2.0, 3.0]),
        }
        tracer = ppl.trace(ppl.substitute(tiny_model, data=params))
        with ppl.force_subsample(
            indices={"shards": jnp.asarray([2, 0, 3, 1])}, scale=False
        ):
            tr = tracer.get_trace(tiny_data)
        np.testing.assert_array_equal(
            np.asarray(tr["b"]["value"]), [2.0, 0.0, 3.0, 1.0]
        )
        np.testing.assert_array_equal(
            np.asarray(tr["obs"]["value"]),
            np.asarray(tiny_data)[[2, 0, 3, 1]],
        )

    def test_permuted_indices_with_condition_data_is_loud(
        self, tiny_data
    ):
        """Review regression: an observed value that BYPASSED
        subsample() is shape-ambiguous under a full-length permuted
        index set (index-ordered vs full-order) — refuse loudly
        instead of silently misaligning rows."""

        def latent_model(x):
            w = ppl.sample("w", Normal(0.0, 1.0))
            with ppl.plate("shards", 4):
                b = ppl.sample("b", Normal(0.0, 1.0))
                ppl.sample("obs", Normal(w + b[:, None], 1.0))

        conditioned = ppl.condition(
            latent_model, data={"obs": tiny_data}
        )
        params = {"w": jnp.asarray(0.1), "b": jnp.zeros((4,))}
        tracer = ppl.trace(ppl.substitute(conditioned, data=params))
        with pytest.raises(PPLError, match="ambiguous"):
            with ppl.force_subsample(
                indices={"shards": jnp.asarray([3, 2, 1, 0])},
                scale=False,
            ):
                tracer.get_trace(tiny_data)

    def test_sample_prior_matches_template(self, tiny_data):
        c = ppl.compile(tiny_model, (tiny_data,))
        p = c.sample_prior(jax.random.PRNGKey(2))
        q = c.init_params()
        assert set(p) == set(q) == {"w", "b"}
        assert p["b"].shape == q["b"].shape == (4,)

    def test_radon_matches_handwritten_glm(self):
        """The effectful radon model equals models/glm.py's
        hand-written logp up to the (gradient-free) HalfNormal
        normalizing constants it drops — values shift by a known
        constant, gradients match exactly."""
        from pytensor_federated_tpu.models.glm import (
            HierarchicalRadonGLM,
            generate_radon_data,
        )
        from pytensor_federated_tpu.ppl.radon import make_radon_example

        model, args, _ = make_radon_example(8, mean_obs=6, seed=3)
        c = ppl.compile(model, args)
        p = c.sample_prior(jax.random.PRNGKey(5))
        data, _ = generate_radon_data(8, mean_obs=6, seed=3)
        glm = HierarchicalRadonGLM(data)
        v, g = c.logp_and_grad(p)
        vg, gg = glm.logp_and_grad(dict(p))
        const = 2 * 0.5 * math.log(2.0 / math.pi)
        np.testing.assert_allclose(
            float(v), float(vg) + const, rtol=1e-5
        )
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(gg[k]),
                rtol=1e-4, atol=1e-5,
            )


# ---------------------------------------------------------------------------
# hypothesis: unbiasedness as a property
# ---------------------------------------------------------------------------


def test_subsample_unbiasedness_property(tiny_data):
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    c = ppl.compile(tiny_model, (tiny_data,))

    @settings(max_examples=15, deadline=None)
    @given(
        w=st.floats(-3.0, 3.0),
        bseed=st.integers(0, 2**16),
        m=st.integers(1, 4),
    )
    def check(w, bseed, m):
        params = {
            "w": jnp.asarray(w, jnp.float32),
            "b": jax.random.normal(jax.random.PRNGKey(bseed), (4,)),
        }
        full = float(c.logp(params))
        vals = [
            float(c.logp_indices(params, jnp.asarray(idx)))
            for idx in itertools.combinations(range(4), m)
        ]
        np.testing.assert_allclose(
            np.mean(vals), full, rtol=1e-4, atol=1e-3
        )

    check()


# ---------------------------------------------------------------------------
# placements: the same program on every lane
# ---------------------------------------------------------------------------


class TestPlacements:
    @pytest.fixture(scope="class")
    def radon(self):
        from pytensor_federated_tpu.ppl.radon import make_radon_example

        model, args, _ = make_radon_example(16, mean_obs=6, seed=3)
        dense = ppl.compile(model, args)
        params = dense.sample_prior(jax.random.PRNGKey(2))
        v, g = dense.logp_and_grad(params)
        return model, args, dense, params, float(v), g

    @pytest.fixture(scope="class")
    def node(self, radon):
        from pytensor_federated_tpu.service.tcp import serve_tcp_once

        _model, _args, dense, *_ = radon
        ports, ready = [], threading.Event()
        threading.Thread(
            target=serve_tcp_once,
            args=(dense.node_compute(),),
            daemon=True,
            kwargs=dict(
                ready_callback=lambda p: (ports.append(p), ready.set()),
                concurrent=True,
            ),
        ).start()
        assert ready.wait(30)
        return ports[0]

    def _check(self, compiled, params, want_v, want_g):
        v, g = compiled.logp_and_grad(params)
        np.testing.assert_allclose(float(v), want_v, rtol=1e-5)
        for k in want_g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(want_g[k]),
                rtol=1e-4, atol=1e-5,
            )

    def test_mesh_placement(self, radon, mesh8):
        model, args, _dense, params, v, g = radon
        c = ppl.compile(
            model, args, placement=fed.MeshPlacement(mesh8)
        )
        self._check(c, params, v, g)

    def test_mesh_indivisible_is_loud(self, mesh8):
        def m(y):
            with ppl.plate("n", 6) as p:
                ppl.sample(
                    "obs", Normal(ppl.sample("w", Normal()), 1.0),
                    obs=ppl.subsample(y, p),
                )

        with pytest.raises(PPLError, match="not divisible"):
            ppl.compile(
                m, (jnp.zeros((6, 2)),),
                placement=fed.MeshPlacement(mesh8),
            )

    def test_pool_placement(self, radon, node):
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        model, args, _dense, params, v, g = radon
        cli = TcpArraysClient("127.0.0.1", node)
        try:
            c = ppl.compile(
                model, args,
                placement=fed.PoolPlacement(cli, window=8),
            )
            self._check(c, params, v, g)
        finally:
            cli.close()

    def test_pool_reduced_windows(self, radon, node):
        """PoolPlacement(reduce=True): the compiler's canonical round
        keeps every inexact mapped operand broadcast-derived, so the
        PR-13 reduced-window lowering stays eligible."""
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )

        model, args, _dense, params, v, g = radon
        pool = NodePool([("127.0.0.1", node)], transport="tcp")
        try:
            c = ppl.compile(
                model, args,
                placement=fed.PoolPlacement(
                    PooledArraysClient(pool), window=8, reduce=True
                ),
            )
            self._check(c, params, v, g)
        finally:
            pool.close()

    def test_mixed_placement(self, radon, node, mesh8):
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        model, args, _dense, params, v, g = radon
        cli = TcpArraysClient("127.0.0.1", node)
        try:
            c = ppl.compile(
                model, args,
                placement=fed.MixedPlacement(
                    fed.MeshPlacement(mesh8),
                    fed.PoolPlacement(cli, window=8),
                    pool_shards=8,
                ),
            )
            self._check(c, params, v, g)
        finally:
            cli.close()

    def test_seeded_prior_identical_across_placements(
        self, radon, node, mesh8
    ):
        """sample_prior is placement-independent: same key, same
        draws, whatever lane the logp runs on."""
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        model, args, dense, *_ = radon
        cli = TcpArraysClient("127.0.0.1", node)
        try:
            lanes = [
                dense,
                ppl.compile(
                    model, args, placement=fed.MeshPlacement(mesh8)
                ),
                ppl.compile(
                    model, args,
                    placement=fed.PoolPlacement(cli, window=8),
                ),
            ]
            draws = [
                lane.sample_prior(jax.random.PRNGKey(11))
                for lane in lanes
            ]
            for other in draws[1:]:
                for k in draws[0]:
                    np.testing.assert_array_equal(
                        np.asarray(draws[0][k]), np.asarray(other[k])
                    )
        finally:
            cli.close()

    def test_lint_fixtures_trace_clean(self):
        """The registered ppl fixtures trace with zero driver-varying
        captures (the fed-placement rule's contract)."""
        from pytensor_federated_tpu.analysis.rules_fedflow import (
            placement_findings,
        )
        from pytensor_federated_tpu.fed.lint_fixtures import FIXTURES

        for fixture in FIXTURES:
            if not fixture.name.startswith("ppl-"):
                continue
            fn, args = fixture.build()
            assert placement_findings(fn, args, fixture=fixture.name) == []
