"""ISSUE 6 acceptance: ONE model definition runs and ``jax.grad``\\s
identically (f32-strict tolerance) under MeshPlacement, PoolPlacement,
and MixedPlacement — and the fusion pass provably coalesces two
independent ``fed_map`` calls into one pipelined window (flightrec /
span evidence).

The pool lane here is REAL transport: in-process TCP nodes (the
tutorial §16 pattern) deployed with ``make_node_compute`` from the
SAME per-shard function the mesh lane maps, behind a routed
``PooledArraysClient``.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu import fed
from pytensor_federated_tpu.bridge import core as bridge_core
from pytensor_federated_tpu.parallel import make_mesh
from pytensor_federated_tpu.routing import NodePool, PooledArraysClient
from pytensor_federated_tpu.service import serve_tcp_once
from pytensor_federated_tpu.telemetry import flightrec

N = 8
RTOL = 1e-5  # f32-strict: identical math, differing reduction orders
GTOL = 1e-4


def _shard_logp(p, xs, ys):
    pred = p[0] + p[1] * xs
    return -jnp.sum((ys - pred) ** 2)


def _node_fn(p, d):
    # FederatedLogpGrad-style signature: (*params, shard_data_pytree).
    return _shard_logp(p, d[0], d[1])


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(N, 12)).astype(np.float32)
    y = (1.0 - 2.0 * x + 0.1 * rng.normal(size=(N, 12))).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


@pytest.fixture(scope="module")
def params():
    return jnp.asarray(np.float32([0.4, -1.1]))


@pytest.fixture(scope="module")
def pool_client(data):
    """Two TCP replicas serving the node-side twin of the per-shard
    logp, behind a routed pool client."""
    compute = fed.make_node_compute(_shard_logp)
    ports = {}
    for name in ("a", "b"):
        ready = threading.Event()
        threading.Thread(
            target=serve_tcp_once,
            args=(compute,),
            daemon=True,
            kwargs=dict(
                ready_callback=lambda p, r=ready, n=name: (
                    ports.update({n: p}),
                    r.set(),
                ),
                concurrent=True,
            ),
        ).start()
        assert ready.wait(30)
    pool = NodePool(
        [("127.0.0.1", ports["a"]), ("127.0.0.1", ports["b"])],
        transport="tcp",
        breaker_kwargs=dict(failure_threshold=1, backoff_s=30.0),
    )
    client = PooledArraysClient(pool)
    yield client
    client.close()
    pool.close()


def _model_for(x, y):
    def model(p):
        pb = fed.fed_broadcast(p, N)
        lps = fed.fed_map(
            lambda s: _shard_logp(s[0], s[1], s[2]), (pb, x, y)
        )
        return fed.fed_sum(lps)

    return model


class TestEquivalenceGate:
    def test_one_model_three_placements(
        self, data, params, devices8, pool_client
    ):
        x, y = data
        model = _model_for(x, y)
        ref_v = float(model(params))
        ref_g = np.asarray(jax.grad(model)(params))

        mesh8 = fed.MeshPlacement(make_mesh({"shards": 8}, devices=devices8))
        mesh4 = fed.MeshPlacement(make_mesh({"shards": 4}, devices=devices8[:4]))
        placements = {
            "mesh": mesh8,
            "pool": fed.PoolPlacement(pool_client, window=8),
            "mixed": fed.MixedPlacement(
                mesh4,
                fed.PoolPlacement(pool_client, window=8),
                pool_shards=4,
            ),
        }
        for name, placement in placements.items():
            run = fed.program(model, placement)
            v = float(run(params))
            g = np.asarray(jax.grad(run)(params))
            np.testing.assert_allclose(v, ref_v, rtol=RTOL, err_msg=name)
            np.testing.assert_allclose(g, ref_g, rtol=GTOL, err_msg=name)

    def test_value_and_grad_through_pool(self, data, params, pool_client):
        x, y = data
        run = fed.program(
            _model_for(x, y), fed.PoolPlacement(pool_client, window=4)
        )
        v, g = jax.value_and_grad(run)(params)
        model = _model_for(x, y)
        np.testing.assert_allclose(float(v), float(model(params)), rtol=RTOL)
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(jax.grad(model)(params)), rtol=GTOL
        )


class TestFusionEvidence:
    def test_two_maps_one_window(self, data, params, pool_client):
        """Two independent fed_maps fuse into ONE pipelined window —
        the flight record shows a single fed.fused_window carrying both
        calls' requests, and the span tree one fed.window."""
        x, y = data
        x2 = x + 0.5

        def model(p):
            pb = fed.fed_broadcast(p, N)
            a = fed.fed_sum(
                fed.fed_map(lambda s: _shard_logp(*s), (pb, x, y))
            )
            b = fed.fed_sum(
                fed.fed_map(lambda s: _shard_logp(*s), (pb, x2, y))
            )
            return a + b

        run = fed.program(model, fed.PoolPlacement(pool_client, window=8))
        flightrec.clear()
        v = float(run(params))
        np.testing.assert_allclose(v, float(model(params)), rtol=RTOL)

        fused = [
            e for e in flightrec.events() if e["kind"] == "fed.fused_window"
        ]
        assert len(fused) == 1, fused
        assert fused[0]["calls"] == 2
        assert fused[0]["requests"] == 2 * N
        window_spans = [
            e
            for e in flightrec.events()
            if e["kind"] == "span.close" and e.get("name") == "fed.window"
        ]
        assert len(window_spans) == 1

        # grad flows through the fused window and stays correct
        np.testing.assert_allclose(
            np.asarray(jax.grad(run)(params)),
            np.asarray(jax.grad(model)(params)),
            rtol=GTOL,
        )

    def test_fuse_off_pays_two_windows(self, data, params, pool_client):
        x, y = data

        def model(p):
            pb = fed.fed_broadcast(p, N)
            a = fed.fed_sum(
                fed.fed_map(lambda s: _shard_logp(*s), (pb, x, y))
            )
            b = fed.fed_sum(
                fed.fed_map(lambda s: _shard_logp(*s), (pb, x, y))
            )
            return a + b

        run = fed.program(
            model, fed.PoolPlacement(pool_client, window=8), fuse=False
        )
        flightrec.clear()
        run(params)
        fused = [
            e for e in flightrec.events() if e["kind"] == "fed.fused_window"
        ]
        assert len(fused) == 2
        assert all(e["calls"] == 1 for e in fused)


class TestPoolContractEnforcement:
    def test_varying_closure_const_raises(self, data, params, pool_client):
        """A pool-placed fed_map that CLOSES over driver state (instead
        of broadcasting it) must fail loudly at lowering: the node
        cannot know the value, so computing would be silently wrong
        (wrong forward, zero gradient)."""
        x, y = data

        def model(p):
            # p captured by closure — varying, but unmapped.
            lps = fed.fed_map(
                lambda s: _shard_logp(p, s[0], s[1]), (x, y)
            )
            return fed.fed_sum(lps)

        run = fed.program(model, fed.PoolPlacement(pool_client, window=8))
        with pytest.raises(ValueError, match="fed_broadcast"):
            run(params)

    def test_baked_function_constants_are_fine(self, data, params, pool_client):
        """Concrete trace-time constants inside the per-shard function
        are NOT driver state: the node's deployed copy of the same
        function carries them, so they lower fine."""
        x, y = data

        def shard_fn(p, xs, ys):
            # the array literal is lifted as a trace-time CONST — baked
            # into both the driver's jaxpr and the node's deployment.
            prior_scale = jnp.asarray([0.25, 0.5], jnp.float32)
            return _shard_logp(p, xs, ys) - jnp.sum((p * prior_scale) ** 2)

        import threading as _threading

        from pytensor_federated_tpu.service import (
            TcpArraysClient,
            serve_tcp_once,
        )

        ready = _threading.Event()
        box = {}
        _threading.Thread(
            target=serve_tcp_once,
            args=(fed.make_node_compute(shard_fn),),
            daemon=True,
            kwargs=dict(
                ready_callback=lambda p: (box.update(p=p), ready.set()),
                max_connections=1,
            ),
        ).start()
        assert ready.wait(30)
        client = TcpArraysClient("127.0.0.1", box["p"])

        def model(p):
            pb = fed.fed_broadcast(p, N)
            lps = fed.fed_map(
                lambda s: shard_fn(s[0], s[1], s[2]), (pb, x, y)
            )
            return fed.fed_sum(lps)

        run = fed.program(model, fed.PoolPlacement(client, window=8))
        np.testing.assert_allclose(
            float(run(params)), float(model(params)), rtol=RTOL
        )
        client.close()


class TestBridgeRouting:
    """federated_potential / ParallelFederatedOp route through
    fed.program: the evaluator is the host LogpGradFn AND carries the
    traced jax_fn, and the fused JAX dispatch composes N potentials
    into one program whose maps share a window."""

    def test_evaluator_host_and_jax_surfaces(self, data, params, pool_client):
        x, y = data
        ev = fed.FederatedLogpGrad(
            _node_fn,
            (x, y),
            placement=fed.PoolPlacement(pool_client, window=8),
        )
        model = _model_for(x, y)
        lp, (g,) = ev(np.asarray(params))
        np.testing.assert_allclose(float(lp), float(model(params)), rtol=RTOL)
        np.testing.assert_allclose(
            g, np.asarray(jax.grad(model)(params)), rtol=GTOL
        )
        lp2, grads2 = ev.jax_fn(params)
        np.testing.assert_allclose(float(lp2), float(lp), rtol=RTOL)
        np.testing.assert_allclose(np.asarray(grads2[0]), g, rtol=GTOL)

    def test_fused_jax_callable_one_window(self, data, params, pool_client):
        x, y = data
        # Deliberately DISTINCT placement objects: fusion keys on
        # equivalence (same client/window), since each potential is
        # naturally built with its own PoolPlacement.
        ev_a = fed.FederatedLogpGrad(
            _node_fn,
            (x, y),
            placement=fed.PoolPlacement(pool_client, window=8),
        )
        ev_b = fed.FederatedLogpGrad(
            _node_fn,
            (x + 0.5, y),
            placement=fed.PoolPlacement(pool_client, window=8),
        )
        m_a = bridge_core.member_jax_callable(
            "logp_grad", ev_a.jax_fn, name="a"
        )
        m_b = bridge_core.member_jax_callable(
            "logp_grad", ev_b.jax_fn, name="b"
        )
        assert getattr(m_a, "_fed_evaluator", None) is ev_a
        fused = bridge_core.fused_jax_callable([m_a, m_b], [1, 1])
        flightrec.clear()
        lp_a, g_a, lp_b, g_b = fused(params, params)
        windows = [
            e for e in flightrec.events() if e["kind"] == "fed.fused_window"
        ]
        assert len(windows) == 1 and windows[0]["calls"] == 2
        model_a = _model_for(x, y)
        model_b = _model_for(x + 0.5, y)
        np.testing.assert_allclose(
            float(lp_a), float(model_a(params)), rtol=RTOL
        )
        np.testing.assert_allclose(
            float(lp_b), float(model_b(params)), rtol=RTOL
        )
        np.testing.assert_allclose(
            np.asarray(g_a),
            np.asarray(jax.grad(model_a)(params)),
            rtol=GTOL,
        )
        np.testing.assert_allclose(
            np.asarray(g_b),
            np.asarray(jax.grad(model_b)(params)),
            rtol=GTOL,
        )
