"""PyTensor bridge tests — skip cleanly when pytensor is not installed.

Mirrors the reference's Op contract tests (reference:
test_wrapper_ops.py:174-237): make_node arity/coercion, perform into
output storage, symbolic eval, and ``at.grad`` through the federated op
matching hand-derived gradients of the closed-form quadratic model.
"""

import numpy as np
import pytest

pytensor = pytest.importorskip("pytensor")

import pytensor.tensor as pt  # noqa: E402

from pytensor_federated_tpu.bridge import (  # noqa: E402
    FederatedArraysToArraysOp,
    FederatedLogpGradOp,
    FederatedLogpOp,
    federated_potential,
)


def quadratic_logp_grad(a, b):
    # Closed-form model with hand gradients (pattern from reference
    # test_wrapper_ops.py:34-45).
    logp = -((a - 1.0) ** 2) - 2.0 * np.sum((b - 3.0) ** 2)
    grads = [-2.0 * (a - 1.0), -4.0 * (b - 3.0)]
    return np.asarray(logp), grads


def quadratic_logp(a, b):
    return quadratic_logp_grad(a, b)[0]


class TestLogpGradOp:
    def test_make_node_arity_and_coercion(self):
        op = FederatedLogpGradOp(quadratic_logp_grad)
        # Raw int input must coerce (reference "issue #24",
        # test_wrapper_ops.py:284-289).
        apply = op.make_node(2, pt.dvector("b"))
        assert len(apply.inputs) == 2
        assert len(apply.outputs) == 3
        assert apply.outputs[0].ndim == 0

    def test_int_input_grad_is_float_typed(self):
        """Grad output for an int-coerced input must be float-typed —
        an int-typed grad output would silently truncate the gradient
        in perform (the reference's ``i.type()`` typing replicates the
        trap, reference: wrapper_ops.py:97-105; we upcast instead)."""
        op = FederatedLogpGradOp(quadratic_logp_grad)
        b = pt.dvector("b")
        apply = op.make_node(2, b)
        assert apply.outputs[1].type.dtype.startswith("float")
        g = pytensor.function([b], apply.outputs[1])
        # a=2 -> d logp/da = -2*(2-1) = -2.0 (not truncated to -2 int,
        # and not rounded away on a non-integer value either)
        np.testing.assert_allclose(g(np.array([1.0, 5.0])), -2.0)

    def test_perform_and_eval(self):
        op = FederatedLogpGradOp(quadratic_logp_grad)
        a = pt.dscalar("a")
        b = pt.dvector("b")
        logp, ga, gb = op(a, b)
        f = pytensor.function([a, b], [logp, ga, gb])
        av, bv = 2.0, np.array([1.0, 5.0])
        out_logp, out_ga, out_gb = f(av, bv)
        exp_logp, (exp_ga, exp_gb) = quadratic_logp_grad(av, bv)
        np.testing.assert_allclose(out_logp, exp_logp)
        np.testing.assert_allclose(out_ga, exp_ga)
        np.testing.assert_allclose(out_gb, exp_gb)

    def test_symbolic_grad_matches_hand_grads(self):
        op = FederatedLogpGradOp(quadratic_logp_grad)
        a = pt.dscalar("a")
        b = pt.dvector("b")
        logp = op(a, b)[0]
        ga, gb = pt.grad(logp, [a, b])
        f = pytensor.function([a, b], [ga, gb])
        av, bv = 0.5, np.array([2.0, 4.0])
        out_ga, out_gb = f(av, bv)
        _, (exp_ga, exp_gb) = quadratic_logp_grad(av, bv)
        np.testing.assert_allclose(out_ga, exp_ga)
        np.testing.assert_allclose(out_gb, exp_gb)

    def test_potential_helper(self):
        a = pt.dscalar("a")
        b = pt.dvector("b")
        logp = federated_potential(quadratic_logp_grad, a, b)
        assert logp.ndim == 0


class TestLogpOp:
    def test_eval(self):
        op = FederatedLogpOp(quadratic_logp)
        a = pt.dscalar("a")
        b = pt.dvector("b")
        f = pytensor.function([a, b], op(a, b))
        np.testing.assert_allclose(
            f(2.0, np.array([3.0])), quadratic_logp(2.0, np.array([3.0]))
        )


class TestArraysToArraysOp:
    def test_eval(self):
        def compute(x, y):
            return [x + y, x * y]

        op = FederatedArraysToArraysOp(
            compute, output_types=[pt.dvector, pt.dvector]
        )
        x = pt.dvector("x")
        y = pt.dvector("y")
        s, p = op(x, y)
        f = pytensor.function([x, y], [s, p])
        xv = np.array([1.0, 2.0])
        yv = np.array([3.0, 4.0])
        out_s, out_p = f(xv, yv)
        np.testing.assert_allclose(out_s, xv + yv)
        np.testing.assert_allclose(out_p, xv * yv)


@pytest.mark.skipif(
    not hasattr(pytensor, "function"), reason="pytensor too old"
)
def test_jax_linker_compiles_through_op():
    """mode="JAX" must inline jax_fn — the TPU-critical path (SURVEY §7.4)."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    def jax_logp_grad(a, b):
        logp = -((a - 1.0) ** 2) - 2.0 * jnp.sum((b - 3.0) ** 2)
        return logp, (-2.0 * (a - 1.0), -4.0 * (b - 3.0))

    op = FederatedLogpGradOp(quadratic_logp_grad, jax_fn=jax_logp_grad)
    a = pt.dscalar("a")
    b = pt.dvector("b")
    logp = op(a, b)[0]
    try:
        f = pytensor.function([a, b], logp, mode="JAX")
    except Exception as e:  # pragma: no cover - jax linker availability
        pytest.skip(f"JAX linker unavailable: {e}")
    av, bv = 2.0, np.array([1.0, 5.0])
    np.testing.assert_allclose(
        np.asarray(f(av, bv)), quadratic_logp_grad(av, bv)[0], rtol=1e-6
    )
