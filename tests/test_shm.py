"""Shared-memory arena transport (service/arena.py + service/shm.py).

Covers the ISSUE-9 tentpole surface: the arena's generation protocol
(stale/torn/recycled slots fail loudly), the doorbell client/server
pair (evaluate, pipelined + batched windows, partial progress,
GetLoad, ping), pinned-array promotion (repeat-identity arrays move
zero bytes), the npwire fallback lane (pool probes), pool mixing, and
the fault-injection seams for the four shm-specific fault scenarios.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from pytensor_federated_tpu import faultinject as fi
from pytensor_federated_tpu.service.arena import Arena
from pytensor_federated_tpu.service.npwire import (
    WireError,
    decode_batch,
    encode_batch,
    is_batch_frame,
)
from pytensor_federated_tpu.service.shm import (
    ShmArraysClient,
    decode_descs,
    decode_frame,
    encode_descs,
    encode_frame,
    serve_shm,
    _KIND_EVAL,
    _KIND_REPLY,
)
from pytensor_federated_tpu.service.tcp import RemoteComputeError


def quad_compute(x):
    x = np.asarray(x)
    return [
        np.asarray(-np.sum((x - 3.0) ** 2)),
        (-2.0 * (x - 3.0)).astype(x.dtype),
    ]


def expected(i):
    return -((i - 3.0) ** 2 + 4.0)


@pytest.fixture()
def shm_node():
    """One in-process shm node (daemon thread) -> (host, port)."""
    ports = []
    thread = threading.Thread(
        target=serve_shm,
        args=(quad_compute,),
        kwargs=dict(ready_callback=ports.append),
        daemon=True,
    )
    thread.start()
    deadline = time.time() + 10
    while not ports and time.time() < deadline:
        time.sleep(0.01)
    assert ports, "shm node did not come up"
    yield "127.0.0.1", ports[0]


@pytest.fixture()
def client(shm_node):
    c = ShmArraysClient(*shm_node)
    yield c
    c.close()


# ---------------------------------------------------------------------------
# arena: the generation protocol
# ---------------------------------------------------------------------------


class TestArena:
    def test_write_read_roundtrip(self, tmp_path):
        arena = Arena.create(1 << 20, path=str(tmp_path / "a.shm"))
        payload = np.arange(32, dtype=np.float64)
        slot, gen, deltas = arena.write_many([memoryview(payload).cast("B")])
        view = arena.read_view(slot, deltas[0], payload.nbytes, gen)
        assert np.array_equal(
            np.frombuffer(view, np.float64), payload
        )
        data = arena.read_bytes(slot, deltas[0], payload.nbytes, gen)
        assert data == payload.tobytes()
        arena.close(unlink=True)

    def test_packing_deltas_are_aligned(self, tmp_path):
        arena = Arena.create(1 << 20, path=str(tmp_path / "a.shm"))
        slot, gen, deltas = arena.write_many([b"abc", b"defgh", b""])
        assert deltas == [0, 8, 16]  # 8-aligned array starts
        assert arena.read_bytes(slot, deltas[1], 5, gen) == b"defgh"
        assert arena.read_bytes(slot, deltas[2], 0, gen) == b""
        arena.close(unlink=True)

    def test_stale_generation_is_loud(self, tmp_path):
        arena = Arena.create(1 << 20, path=str(tmp_path / "a.shm"))
        slot, gen, deltas = arena.write_many([b"x" * 64])
        with pytest.raises(WireError, match="stale descriptor"):
            arena.read_view(slot, 0, 64, gen + 1)
        arena.close(unlink=True)

    def test_recycled_slot_is_loud(self, tmp_path):
        """A descriptor held across a free + rewrite sees the NEW
        generation and fails — never torn data."""
        arena = Arena.create(4096, path=str(tmp_path / "a.shm"))
        slot, gen, _d = arena.write_many([b"old" * 100])
        arena.free(slot)
        # Fill until the ring reuses the freed region.
        for _ in range(16):
            s2, g2, _ = arena.write_many([b"new" * 100])
            arena.free(s2)
        with pytest.raises(WireError, match="stale|torn"):
            arena.read_view(slot, 0, 300, gen)
        arena.close(unlink=True)

    def test_torn_write_is_loud(self, tmp_path):
        arena = Arena.create(1 << 20, path=str(tmp_path / "a.shm"))
        slot, gen, _d = arena.write_many([b"y" * 128])
        arena.scribble_tail(slot)  # the truncate_slot chaos primitive
        with pytest.raises(WireError, match="torn slot"):
            arena.read_view(slot, 0, 128, gen)
        arena.close(unlink=True)

    def test_out_of_bounds_descriptor_is_loud(self, tmp_path):
        arena = Arena.create(1 << 20, path=str(tmp_path / "a.shm"))
        slot, gen, _d = arena.write_many([b"z" * 16])
        with pytest.raises(WireError, match="out of arena bounds"):
            arena.read_view(arena.capacity + 64, 0, 16, gen)
        with pytest.raises(WireError, match="exceeds"):
            arena.read_view(slot, 0, 17, gen)  # past the payload
        with pytest.raises(WireError, match="misaligned"):
            arena.read_view(slot + 4, 0, 8, gen)
        arena.close(unlink=True)

    def test_exhaustion_is_loud_never_overwrites(self, tmp_path):
        arena = Arena.create(4096, path=str(tmp_path / "a.shm"))
        slot, gen, _d = arena.write_many([b"a" * 1024])
        with pytest.raises(WireError, match="arena exhausted"):
            arena.write_many([b"b" * 4096])
        # The live slot is intact after the refused allocation.
        assert arena.read_bytes(slot, 0, 1024, gen) == b"a" * 1024
        arena.close(unlink=True)

    def test_exactly_full_ring_refuses(self, tmp_path):
        """head == tail with live slots means FULL, not empty: an
        exact-fit wrap must not let the next allocation overwrite the
        oldest in-flight slot (round-9 review finding)."""
        arena = Arena.create(64 + 320, path=str(tmp_path / "a.shm"))
        sA, _gA, _ = arena.write_many([b"x" * 60])  # slots are 128 B
        sB, gB, _ = arena.write_many([b"y" * 60])
        arena.free(sA)
        sC, gC, _ = arena.write_many([b"z" * 60])  # wraps: head == tail
        assert arena._head == arena._tail and len(arena._live) == 2
        with pytest.raises(WireError, match="exactly full"):
            arena.write_many([b"w" * 60])
        assert arena.read_bytes(sB, 0, 60, gB) == b"y" * 60  # intact
        assert arena.read_bytes(sC, 0, 60, gC) == b"z" * 60
        arena.free(sB)
        s2, g2, _ = arena.write_many([b"k" * 60])  # frees reopen it
        assert arena.read_bytes(s2, 0, 60, g2) == b"k" * 60
        arena.close(unlink=True)

    def test_pinned_alloc_clears_wrapped_live_slots(self, tmp_path):
        """A pinned allocation while the ring is WRAPPED must clear the
        highest live byte, not just the tail pointer — a mid-window pin
        promotion previously landed inside an in-flight slot (round-9
        review finding, reproduced)."""
        arena = Arena.create(4096, path=str(tmp_path / "a.shm"))
        s1, _g1, _ = arena.write_many([b"a" * 900])
        s2, g2, _ = arena.write_many([b"b" * 2800])  # extends to ~3968
        arena.free(s1)
        s3, g3, _ = arena.write_many([b"c" * 500])  # wraps: tail > head
        assert arena._tail > arena._head
        with pytest.raises(WireError, match="pinned region"):
            arena.write_many([b"p" * 600], pinned=True)
        # Both in-flight slots are untouched.
        assert arena.read_bytes(s2, 0, 2800, g2) == b"b" * 2800
        assert arena.read_bytes(s3, 0, 500, g3) == b"c" * 500
        arena.close(unlink=True)

    def test_full_ring_reports_zero_free(self, tmp_path):
        arena = Arena.create(64 + 320, path=str(tmp_path / "a.shm"))
        sA, _gA, _ = arena.write_many([b"x" * 60])
        sB, _gB, _ = arena.write_many([b"y" * 60])
        arena.free(sA)
        arena.write_many([b"z" * 60])  # wraps; head == tail, full
        assert arena.transient_bytes_free() == 0
        arena.close(unlink=True)

    def test_fifo_free_enforced(self, tmp_path):
        arena = Arena.create(1 << 20, path=str(tmp_path / "a.shm"))
        s1, _g1, _ = arena.write_many([b"1"])
        s2, _g2, _ = arena.write_many([b"2"])
        with pytest.raises(WireError, match="out of order"):
            arena.free(s2)
        arena.free(s1)
        arena.free(s2)
        arena.close(unlink=True)

    def test_ring_wraps_and_reuses(self, tmp_path):
        """Many write/free cycles in a small arena: the ring wraps
        without exhaustion and every read validates."""
        arena = Arena.create(8192, path=str(tmp_path / "a.shm"))
        for i in range(200):
            payload = bytes([i % 256]) * 1000
            slot, gen, deltas = arena.write_many([payload])
            assert arena.read_bytes(slot, 0, 1000, gen) == payload
            arena.free(slot)
        arena.close(unlink=True)

    def test_pinned_region_separate_from_ring(self, tmp_path):
        arena = Arena.create(1 << 16, path=str(tmp_path / "a.shm"))
        pslot, pgen, _ = arena.write_many([b"pin" * 10], pinned=True)
        for _ in range(50):  # ring churn must not disturb the pin
            s, g, _ = arena.write_many([b"t" * 500])
            arena.free(s)
        assert arena.read_bytes(pslot, 0, 30, pgen) == b"pin" * 10
        arena.close(unlink=True)

    def test_attach_validates_header(self, tmp_path):
        bad = tmp_path / "bad.shm"
        bad.write_bytes(b"NOPE" + b"\0" * 100)
        with pytest.raises(WireError, match="bad arena magic"):
            Arena.attach(str(bad))

    def test_reader_cannot_allocate(self, tmp_path):
        arena = Arena.create(1 << 16, path=str(tmp_path / "a.shm"))
        reader = Arena.attach(arena.path)
        with pytest.raises(WireError, match="owner"):
            reader.write_many([b"nope"])
        reader.close()
        arena.close(unlink=True)


# ---------------------------------------------------------------------------
# doorbell frames
# ---------------------------------------------------------------------------


class TestDoorbellWire:
    def test_frame_roundtrip(self):
        uid = b"u" * 16
        frame = encode_frame(_KIND_EVAL, uid, b"body", trace_id=b"t" * 16)
        kind, ruid, err, tid, _dl, _part, _ver, off, eff = decode_frame(frame)
        assert (kind, ruid, err, tid) == (_KIND_EVAL, uid, None, b"t" * 16)
        assert eff is frame  # no chaos plan: the effective frame IS buf
        assert frame[off:] == b"body"

    def test_error_block_roundtrip(self):
        frame = encode_frame(_KIND_REPLY, b"u" * 16, error="boom")
        _k, _u, err, _t, _d, _p, _v, _o, _f = decode_frame(frame)
        assert err == "boom"

    def test_unknown_kind_rejected(self):
        frame = bytearray(encode_frame(_KIND_EVAL, b"u" * 16))
        frame[5] = 200  # kind byte
        with pytest.raises(WireError, match="unknown shm frame kind"):
            decode_frame(bytes(frame))

    def test_unknown_flag_rejected(self):
        frame = bytearray(encode_frame(_KIND_EVAL, b"u" * 16))
        frame[6] |= 0x40  # undeclared flag bit
        with pytest.raises(WireError, match="unknown shm flag bits"):
            decode_frame(bytes(frame))

    def test_bad_magic_rejected(self):
        with pytest.raises(WireError, match="bad shm magic"):
            decode_frame(b"XXXX" + b"\0" * 30)

    def test_desc_block_roundtrip(self):
        descs = [
            (64, 0, 1024, 7, np.dtype("<f8"), (128,)),
            (64, 1024, 8, 7, np.dtype("<i4"), (2, 1)),
        ]
        buf = encode_descs(descs)
        out, off = decode_descs(buf, 0)
        assert off == len(buf)
        assert out == descs

    def test_truncated_desc_block_is_loud(self):
        buf = encode_descs([(64, 0, 8, 1, np.dtype("<f8"), (1,))])
        with pytest.raises(WireError, match="truncated"):
            decode_descs(buf[:-3], 0)


# ---------------------------------------------------------------------------
# client/server e2e
# ---------------------------------------------------------------------------


class TestShmE2E:
    def test_evaluate(self, client):
        out = client.evaluate(np.array([1.0, 5.0]))
        assert float(out[0]) == expected(1.0)
        assert np.array_equal(out[1], np.array([4.0, -4.0]))
        # Default copy=True returns owned, writable arrays.
        out[1][0] = 99.0

    def test_copy_false_returns_views(self, shm_node):
        c = ShmArraysClient(*shm_node, copy=False)
        try:
            out = c.evaluate(np.array([1.0, 5.0]))
            assert float(out[0]) == expected(1.0)
            assert not out[1].flags.writeable
        finally:
            c.close()

    def test_dtype_shape_layout_fidelity(self):
        """An echo node proves byte-exact round-trips for 0-d arrays,
        empty arrays, non-float dtypes, and non-contiguous (Fortran /
        sliced) inputs — layout normalized once at encode entry."""

        def echo(*arrays):
            return [np.asarray(a) for a in arrays]

        ports = []
        threading.Thread(
            target=serve_shm, args=(echo,),
            kwargs=dict(ready_callback=ports.append), daemon=True,
        ).start()
        while not ports:
            time.sleep(0.01)
        c = ShmArraysClient("127.0.0.1", ports[0])
        try:
            cases = [
                np.arange(6, dtype=np.float32).reshape(2, 3),
                np.asarray(np.float64(2.5)),
                np.array([], dtype=np.int32),
                np.asfortranarray(
                    np.arange(12, dtype=np.int64).reshape(3, 4)
                ),
                np.arange(20, dtype=np.float64)[::2],  # sliced view
                np.zeros(3, dtype=[("a", "<f4"), ("b", "<i8")]),
            ]
            outs = c.evaluate(*cases)
            for x, out in zip(cases, outs):
                assert out.dtype == x.dtype
                assert out.shape == x.shape
                assert np.array_equal(out, x)
        finally:
            c.close()

    def test_evaluate_many_pipelined_and_batched(self, client):
        reqs = [(np.array([float(i), 5.0]),) for i in range(40)]
        for batch in (False, True, "auto"):
            res = client.evaluate_many(reqs, window=8, batch=batch)
            for i in range(40):
                assert float(res[i][0]) == expected(float(i))

    def test_copy_false_windows_still_copy(self, shm_node):
        """``copy=False`` is a single-evaluate contract: inside a
        pipelined window, acks on later frames let the node recycle
        reply slots earlier results still view — so window replies are
        force-copied (round-9 review finding).  All values must stay
        correct after the whole window settles."""
        c = ShmArraysClient(*shm_node, copy=False)
        try:
            reqs = [(np.array([float(i), 5.0]),) for i in range(64)]
            for batch in (False, True):
                res = c.evaluate_many(reqs, window=4, batch=batch)
                for i in range(64):
                    assert float(res[i][0]) == expected(float(i))
                    assert res[i][1].flags.owndata  # copied, not a view
        finally:
            c.close()

    def test_truncated_batch_reply_is_wire_error(self, shm_node):
        """A reply frame truncated past the header must classify as
        WireError and close the connection — never a raw struct.error
        (round-9 review finding)."""
        plan = fi.FaultPlan(
            [fi.FaultRule("truncate_frame", point="shm.recv", nth=2,
                          cut_frac=0.2)],
            seed=9,
        )  # frame 1 is the ATTACH reply; frame 2 is the batch reply
        c = ShmArraysClient(*shm_node, retries=0)
        fi.install(plan)
        try:
            reqs = [(np.array([float(i), 5.0]),) for i in range(8)]
            with pytest.raises(WireError):
                c.evaluate_many(reqs, window=8, batch=True)
            assert c._sock is None  # closed, not desynchronized
        finally:
            fi.uninstall()
            c.close()

    def test_pinned_arrays_move_zero_bytes(self, client):
        """The second-and-later appearances of the SAME array object
        ride pinned descriptors: the arena write counter stops
        moving."""
        from pytensor_federated_tpu.service.npwire import (
            WIRE_BYTES_COPIED,
        )

        counter = WIRE_BYTES_COPIED.labels(lane="shm", stage="arena_write")
        x = np.zeros(4096, np.float64)
        client.evaluate(x)  # 1st: transient write
        client.evaluate(x)  # 2nd: promotion write (pinned region)
        before = counter.value
        for _ in range(5):
            out = client.evaluate(x)
        assert float(out[0]) == float(-np.sum((x - 3.0) ** 2))
        # Steady state: no request payload bytes moved at all (the
        # reply side still writes its scalars server-side).
        reply_bytes = 5 * (8 + x.nbytes)  # server reply writes
        assert counter.value - before <= reply_bytes

    def test_fresh_arrays_never_spuriously_pin(self, client):
        """CPython recycles ids of freed per-call arrays constantly;
        the weakref-verified hit counter must not promote UNRELATED
        arrays that merely reuse an id (round-9 review finding) — a
        fresh-params-every-call workload pins nothing."""
        for i in range(200):
            out = client.evaluate(np.array([float(i % 7), 5.0]))
            assert float(out[0]) == expected(float(i % 7))
        assert not client._pinned
        assert len(client._pin_hits) <= 4096

    def test_pin_arrays_false_disables_cache(self, shm_node):
        c = ShmArraysClient(*shm_node, pin_arrays=False)
        try:
            x = np.zeros(16)
            for _ in range(3):
                c.evaluate(x)
            assert not c._pinned
        finally:
            c.close()

    def test_remote_error_no_retry_connection_survives(self, shm_node):
        calls = []

        def flaky(x):
            calls.append(1)
            if len(calls) == 1:
                raise ValueError("poisoned input")
            return quad_compute(x)

        ports = []
        threading.Thread(
            target=serve_shm, args=(flaky,),
            kwargs=dict(ready_callback=ports.append), daemon=True,
        ).start()
        while not ports:
            time.sleep(0.01)
        c = ShmArraysClient("127.0.0.1", ports[0])
        try:
            with pytest.raises(RemoteComputeError, match="poisoned"):
                c.evaluate(np.array([1.0, 5.0]))
            assert len(calls) == 1  # deterministic: no retry
            out = c.evaluate(np.array([1.0, 5.0]))  # same connection
            assert float(out[0]) == expected(1.0)
        finally:
            c.close()

    def test_batch_per_item_error_isolation(self, shm_node):
        def picky(x):
            x = np.asarray(x)
            if float(x[0]) == 7.0:
                raise ValueError("item poisoned")
            return quad_compute(x)

        ports = []
        threading.Thread(
            target=serve_shm, args=(picky,),
            kwargs=dict(ready_callback=ports.append), daemon=True,
        ).start()
        while not ports:
            time.sleep(0.01)
        c = ShmArraysClient("127.0.0.1", ports[0])
        try:
            reqs = [(np.array([float(i), 5.0]),) for i in range(12)]
            with pytest.raises(RemoteComputeError, match="item poisoned"):
                c.evaluate_many(reqs, window=12, batch=True)
            # The connection stays correlated for the next window.
            ok = c.evaluate_many(reqs[:6], window=6, batch=True)
            for i in range(6):
                assert float(ok[i][0]) == expected(float(i))
        finally:
            c.close()

    def test_evaluate_many_partial_dead_node(self):
        """SIGKILL mid-window: partial results + a transport exc, the
        pool failover contract."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        proc = ctx.Process(
            target=_serve_shm_slow_node, args=(port,), daemon=True
        )
        proc.start()
        try:
            deadline = time.time() + 60
            c = ShmArraysClient(
                "127.0.0.1", port, retries=0,
                connect_timeout_s=2.0, connect_retries=20,
                connect_backoff_s=0.2,
            )
            reqs = [(np.array([float(i), 5.0]),) for i in range(16)]
            # Warm one call so the node is definitely serving.
            while time.time() < deadline:
                try:
                    c.evaluate(np.array([0.0, 5.0]))
                    break
                except (ConnectionError, OSError):
                    time.sleep(0.2)
            killer = threading.Timer(0.15, proc.kill)
            killer.start()
            res, exc = c.evaluate_many_partial(reqs, window=4)
            killer.cancel()
            assert exc is not None  # the kill surfaced as transport
            served = [r for r in res if r is not None]
            for i, r in enumerate(res):
                if r is not None:
                    assert float(r[0]) == expected(float(i))
            assert len(served) < len(reqs)
            c.close()
        finally:
            if proc.is_alive():
                proc.kill()
            proc.join(timeout=10)

    def test_get_load_and_ping(self, client):
        load = client.get_load()
        assert load is not None and load["transport"] == "shm"
        assert load["batch"]["max_batch"] >= 1
        rtt = client.ping()
        assert 0 < rtt < 5.0

    def test_ping_corrupt_reply_closes_not_leaks(self, shm_node):
        """An undecodable PONG closes the connection instead of
        leaking the ping's transient slot into the FIFO free order
        (round-9 review finding): the next call works cleanly."""
        plan = fi.FaultPlan(
            [fi.FaultRule("corrupt_bytes", point="shm.recv", nth=2)],
            seed=8,
        )  # nth=2: the ATTACH reply is frame 1, the PONG is frame 2
        c = ShmArraysClient(*shm_node, retries=0)
        fi.install(plan)
        try:
            with pytest.raises((WireError, RuntimeError)):
                c.ping()
            assert c._sock is None  # closed, not desynchronized
        finally:
            fi.uninstall()
        out = c.evaluate(np.array([1.0, 5.0]))  # fresh attach, clean
        assert float(out[0]) == expected(1.0)
        c.close()

    def test_npwire_probe_fallback(self, shm_node):
        """The pool's zero-item batch probe works against the doorbell
        (the mixed-pool health-check lane)."""
        host, port = shm_node
        uid = b"p" * 16
        frame = encode_batch([], uuid=uid)
        with socket.create_connection((host, port), timeout=5) as s:
            s.sendall(struct.pack("<I", len(frame)) + frame)
            hdr = s.recv(4)
            (n,) = struct.unpack("<I", hdr)
            payload = b""
            while len(payload) < n:
                payload += s.recv(n - len(payload))
        assert is_batch_frame(payload)
        items, ruid, err, _t, _sp = decode_batch(payload)
        assert ruid == uid and err is None and items == []


def _serve_shm_slow_node(port):
    """Module-level (spawn target): an shm node whose compute sleeps,
    so a SIGKILL lands mid-window."""
    import time as _time

    import numpy as _np

    from pytensor_federated_tpu.service.shm import serve_shm as _serve

    def compute(x):
        _time.sleep(0.05)
        x = _np.asarray(x)
        return [
            _np.asarray(-_np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    _serve(compute, "127.0.0.1", port)


# ---------------------------------------------------------------------------
# pool integration
# ---------------------------------------------------------------------------


class TestShmPool:
    def test_mixed_pool_probe_route_failover(self, shm_node):
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )
        from pytensor_federated_tpu.service.tcp import serve_tcp_once

        tports = []
        threading.Thread(
            target=serve_tcp_once, args=(quad_compute,),
            kwargs=dict(ready_callback=tports.append, concurrent=True),
            daemon=True,
        ).start()
        while not tports:
            time.sleep(0.01)
        pool = NodePool(transport="tcp", probe_timeout_s=2.0)
        pool.add_replica(*shm_node, transport="shm")
        pool.add_replica("127.0.0.1", tports[0])
        try:
            assert pool.probe_once() == 2
            kinds = {r.transport for r in pool.replicas}
            assert kinds == {"shm", "tcp"}
            client = PooledArraysClient(pool)
            reqs = [(np.array([float(i), 5.0]),) for i in range(24)]
            res = client.evaluate_many(reqs, window=6)
            for i in range(24):
                assert float(res[i][0]) == expected(float(i))
        finally:
            pool.close()


    def test_mixed_pool_kwargs_stay_per_transport(self, shm_node):
        """Pool-level client_kwargs target the pool's OWN transport
        class; a mixed-in replica of another transport must not
        inherit them (round-9 review finding: a grpc codec= kwarg
        crashed the shm constructor)."""
        from pytensor_federated_tpu.routing import NodePool

        pool = NodePool(
            transport="grpc", client_kwargs={"codec": "npproto"}
        )
        replica = pool.add_replica(*shm_node, transport="shm")
        try:
            client = pool.client_for(replica)  # must not TypeError
            assert type(client).__name__ == "ShmArraysClient"
            out = client.evaluate(np.array([1.0, 5.0]))
            assert float(out[0]) == expected(1.0)
        finally:
            pool.close()
        # Per-replica kwargs override explicitly when wanted.
        pool2 = NodePool(transport="tcp")
        try:
            r2 = pool2.add_replica(
                *shm_node, transport="shm",
                client_kwargs={"pin_arrays": False},
            )
            assert pool2.client_for(r2).pin_arrays is False
        finally:
            pool2.close()

    def test_conflicting_reregistration_raises(self, shm_node):
        from pytensor_federated_tpu.routing import NodePool

        pool = NodePool(transport="tcp")
        try:
            pool.add_replica(*shm_node, transport="shm")
            pool.add_replica(*shm_node, transport="shm")  # idempotent
            with pytest.raises(ValueError, match="already registered"):
                pool.add_replica(*shm_node, transport="tcp")
        finally:
            pool.close()

    def test_raw_ack_frame_lane(self, shm_node):
        """The ACK doorbell kind at the wire level: the server
        processes it with NO reply, and the connection stays
        correlated for the next EVAL (windows send one at their
        end)."""
        host, port = shm_node
        c = ShmArraysClient(host, port)
        try:
            reqs = [(np.array([float(i), 5.0]),) for i in range(6)]
            c.evaluate_many(reqs, window=3, batch=False)  # ends in ACK
            out = c.evaluate(np.array([2.0, 5.0]))  # still correlated
            assert float(out[0]) == expected(2.0)
        finally:
            c.close()


def test_fast_uuid_reseeds_after_fork():
    """A fork-started worker must not replay the parent's id stream
    (round-9 review finding): the prefix and counter re-derive in the
    child via os.register_at_fork."""
    import os as _os

    if not hasattr(_os, "fork"):
        pytest.skip("no fork on this platform")
    from pytensor_federated_tpu.service.npwire import fast_uuid

    fast_uuid()  # advance the parent counter
    r, w = _os.pipe()
    pid = _os.fork()
    if pid == 0:  # child
        try:
            _os.write(w, fast_uuid())
        finally:
            _os._exit(0)
    child_uuid = _os.read(r, 16)
    _os.close(r)
    _os.close(w)
    _os.waitpid(pid, 0)
    parent_next = fast_uuid()
    assert len(child_uuid) == 16
    assert child_uuid[:12] != parent_next[:12]  # fresh child prefix


# ---------------------------------------------------------------------------
# fault-injection seams (the four shm fault scenarios, classified loud)
# ---------------------------------------------------------------------------


class TestShmChaos:
    def _client(self, shm_node, **kw):
        return ShmArraysClient(*shm_node, retries=0, **kw)

    def test_corrupt_descriptor_classified(self, shm_node):
        plan = fi.FaultPlan(
            [fi.FaultRule("corrupt_descriptor", point="shm.descriptor",
                          nth=1)],
            seed=3,
        )
        fi.install(plan)
        c = self._client(shm_node)
        try:
            with pytest.raises(
                (RemoteComputeError, WireError, RuntimeError,
                 ConnectionError)
            ):
                c.evaluate(np.array([1.0, 5.0]))
            assert plan.total_fires == 1
        finally:
            fi.uninstall()
            c.close()

    def test_client_side_truncated_request_slot_classified(self, shm_node):
        """The shm.arena.write point (client request-arena writes):
        a torn REQUEST slot is answered with an in-band decode error
        — classified loud, connection survives."""
        plan = fi.FaultPlan(
            [fi.FaultRule("truncate_slot", point="shm.arena.write",
                          nth=1)],
            seed=11,
        )
        fi.install(plan)
        c = self._client(shm_node)
        try:
            with pytest.raises(RemoteComputeError, match="torn slot"):
                c.evaluate(np.array([1.0, 5.0]))
            assert plan.total_fires == 1
        finally:
            fi.uninstall()
        out = c.evaluate(np.array([1.0, 5.0]))  # same connection
        assert float(out[0]) == expected(1.0)
        c.close()

    def test_truncated_slot_classified(self, shm_node):
        plan = fi.FaultPlan(
            [fi.FaultRule("truncate_slot", point="shm.arena.reply",
                          nth=1)],
            seed=4,
        )
        fi.install(plan)
        c = self._client(shm_node)
        try:
            with pytest.raises(WireError, match="torn slot"):
                c.evaluate(np.array([1.0, 5.0]))
        finally:
            fi.uninstall()
            c.close()

    def test_stale_generation_classified(self, shm_node):
        plan = fi.FaultPlan(
            [fi.FaultRule("stale_generation", point="shm.arena.reply",
                          nth=1)],
            seed=5,
        )
        fi.install(plan)
        c = self._client(shm_node)
        try:
            with pytest.raises(WireError, match="stale descriptor"):
                c.evaluate(np.array([1.0, 5.0]))
        finally:
            fi.uninstall()
            c.close()

    def test_doorbell_disconnect_classified(self, shm_node):
        plan = fi.FaultPlan(
            [fi.FaultRule("disconnect", point="shm.send", nth=1)],
            seed=6,
        )
        fi.install(plan)
        c = self._client(shm_node)
        try:
            with pytest.raises(ConnectionError):
                c.evaluate(np.array([1.0, 5.0]))
        finally:
            fi.uninstall()
            c.close()

    def test_recovery_after_chaos(self, shm_node):
        """After a doorbell disconnect, the retrying client re-attaches
        a fresh arena pair and the value is correct."""
        plan = fi.FaultPlan(
            [fi.FaultRule("disconnect", point="shm.send", nth=1)],
            seed=7,
        )
        fi.install(plan)
        c = ShmArraysClient(*shm_node, retries=2)
        try:
            out = c.evaluate(np.array([1.0, 5.0]))
            assert float(out[0]) == expected(1.0)
            assert plan.total_fires == 1
        finally:
            fi.uninstall()
            c.close()
