"""Posterior-predictive simulators on the GLM families.

``model.predictive(params, key)`` plugs directly into
``samplers.posterior_predictive`` (the pm.sample_posterior_predictive
workflow).  Tests check shape/mask contracts and distributional
calibration at the true parameters (simulated moments match the
observation model's).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.models.countdata import (
    FederatedNegBinGLM,
    FederatedPoissonGLM,
    generate_count_data,
)
from pytensor_federated_tpu.models.logistic import (
    HierarchicalLogisticRegression,
    generate_hier_logistic_data,
)
from pytensor_federated_tpu.models.gamma import (
    FederatedGammaGLM,
    generate_gamma_data,
)
from pytensor_federated_tpu.models.ordinal import (
    FederatedOrdinalRegression,
    generate_ordinal_data,
)
from pytensor_federated_tpu.models.robust import (
    FederatedRobustRegression,
    generate_robust_data,
)
from pytensor_federated_tpu.models.survival import (
    FederatedWeibullAFT,
    generate_survival_data,
)
from pytensor_federated_tpu.samplers.predictive import posterior_predictive


def _fit_params(model):
    return model.find_map()


@pytest.mark.parametrize(
    "cls,gen",
    [
        (
            HierarchicalLogisticRegression,
            lambda: generate_hier_logistic_data(4, n_obs=48, n_features=3),
        ),
        (
            FederatedPoissonGLM,
            lambda: generate_count_data(4, n_obs=48, n_features=3),
        ),
        (
            FederatedNegBinGLM,
            lambda: generate_count_data(
                4, n_obs=48, n_features=3, dispersion=4.0
            ),
        ),
        (
            FederatedRobustRegression,
            lambda: generate_robust_data(
                4, n_obs=48, n_features=3, outlier_frac=0.0
            ),
        ),
    ],
    ids=lambda c: getattr(c, "__name__", ""),
)
def test_predictive_shape_and_mask(cls, gen):
    data, _ = gen()
    m = cls(data)
    (X, y), mask = data.tree()
    sim = m.predictive(m.init_params(), jax.random.PRNGKey(0))
    assert sim.shape == y.shape
    # padded slots must be zeroed
    np.testing.assert_array_equal(
        np.asarray(sim)[np.asarray(mask) == 0], 0.0
    )


def test_poisson_predictive_calibrated():
    # At the MAP, replicated data's masked mean must match the observed
    # mean closely (Poisson: E[y] = mu, and MAP fits mu to the data).
    data, _ = generate_count_data(4, n_obs=64, n_features=3, seed=11)
    m = FederatedPoissonGLM(data)
    est = _fit_params(m)
    (X, y), mask = data.tree()
    sims = posterior_predictive(
        m.predictive,
        jax.tree_util.tree_map(lambda a: a[None, None], est),
        jax.random.PRNGKey(1),
    )
    # sims: (1, S, N) — broadcast the single draw
    sim_mean = float(jnp.sum(sims[0]) / jnp.sum(mask))
    obs_mean = float(jnp.sum(y * mask) / jnp.sum(mask))
    assert abs(sim_mean - obs_mean) / obs_mean < 0.2


def test_posterior_predictive_sweep_over_chain():
    data, _ = generate_count_data(2, n_obs=32, n_features=2, seed=13)
    m = FederatedPoissonGLM(data)
    res = m.sample(
        key=jax.random.PRNGKey(2),
        num_warmup=100,
        num_samples=50,
        num_chains=2,
    )
    sims = posterior_predictive(
        m.predictive, res.samples, jax.random.PRNGKey(3), num_draws=20
    )
    (X, y), mask = data.tree()
    assert sims.shape == (20,) + y.shape
    # observed masked mean inside the predictive interval of means
    means = np.asarray(
        jnp.sum(sims, axis=(1, 2)) / jnp.sum(mask)
    )
    obs_mean = float(jnp.sum(y * mask) / jnp.sum(mask))
    assert means.min() - 0.5 < obs_mean < means.max() + 0.5


class TestPriorPredictive:
    @pytest.mark.parametrize(
        "cls,kwargs,gen",
        [
            (HierarchicalLogisticRegression, {},
             lambda: generate_hier_logistic_data(4, n_obs=32, n_features=2)),
            (FederatedPoissonGLM, {},
             lambda: generate_count_data(4, n_obs=32, n_features=2)),
            (FederatedNegBinGLM, {},
             lambda: generate_count_data(
                 4, n_obs=32, n_features=2, dispersion=4.0)),
            (FederatedRobustRegression, {},
             lambda: generate_robust_data(4, n_obs=32, n_features=2)),
            (FederatedGammaGLM, {},
             lambda: generate_gamma_data(4, n_obs=32, n_features=2)),
            (FederatedWeibullAFT, {},
             lambda: generate_survival_data(4, n_obs=32, n_features=2)),
            (FederatedOrdinalRegression, {"n_categories": 4},
             lambda: generate_ordinal_data(4, n_obs=32, n_categories=4)),
        ],
        ids=lambda c: getattr(c, "__name__", ""),
    )
    def test_prior_predictive_runs(self, cls, kwargs, gen):
        from pytensor_federated_tpu.samplers import prior_predictive

        data, _ = gen()
        m = cls(data, **kwargs)
        sims = prior_predictive(
            m.sample_prior, m.predictive, jax.random.PRNGKey(0),
            num_draws=20,
        )
        (X, y), mask = data.tree()
        assert sims.shape == (20,) + np.shape(np.asarray(mask))
        # prior draws must score finite under the prior
        p = m.sample_prior(jax.random.PRNGKey(1))
        assert np.isfinite(float(m.prior_logp(p)))
        # simulated values must be real draws, not int32-clamp
        # sentinels or inf->0 artifacts; the sentinel bound applies to
        # the count families (jax.random.poisson clamps at INT32_MAX —
        # continuous families legitimately exceed it under wide priors)
        assert np.all(np.isfinite(np.asarray(sims)))
        if cls in (FederatedPoissonGLM, FederatedNegBinGLM):
            assert float(np.max(np.asarray(sims))) < 2**31 - 1

    def test_prior_draw_shapes_match_init(self):
        data, _ = generate_count_data(4, n_obs=32, n_features=2)
        m = FederatedNegBinGLM(data)
        p0 = m.init_params()
        p1 = m.sample_prior(jax.random.PRNGKey(2))
        assert set(p0) == set(p1)
        for k in p0:
            assert np.shape(np.asarray(p0[k])) == np.shape(np.asarray(p1[k]))
