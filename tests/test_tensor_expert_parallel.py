"""Tensor parallelism (feature sharding) + expert parallelism
(component sharding) — the two taxonomy axes the reference lacks
entirely (SURVEY.md §2 "not present — design fresh").

Equality against the unsharded build is the ground truth (the golden-
model pattern, reference: test_demo_node.py:29-65); sharding assertions
pin that the parallel build really is parallel (inputs/params/grads
stay sharded — a silent full replication would pass the value test).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu.parallel.expert import (
    ExpertShardedMixture,
    generate_expert_mixture_data,
)
from pytensor_federated_tpu.parallel.mesh import make_mesh
from pytensor_federated_tpu.parallel.tensor import (
    TensorParallelLogistic,
    generate_wide_logistic_data,
)


class TestTensorParallel:
    def test_matches_unsharded(self, devices8):
        mesh = make_mesh({"tp": 8}, devices=devices8)
        X, y, _ = generate_wide_logistic_data(128, 64)
        tp = TensorParallelLogistic(X, y, mesh=mesh)
        ref = TensorParallelLogistic(X, y)
        p_ref = ref.init_params()
        p_tp = tp.init_params()
        for shift in (0.0, 0.25):
            pr = jax.tree_util.tree_map(lambda a: a + shift, p_ref)
            pt = jax.tree_util.tree_map(lambda a: a + shift, p_tp)
            np.testing.assert_allclose(
                float(tp.logp(pt)), float(ref.logp(pr)), rtol=2e-5
            )
            _, g_tp = tp.logp_and_grad(pt)
            _, g_ref = ref.logp_and_grad(pr)
            np.testing.assert_allclose(
                np.asarray(g_tp["w"]), np.asarray(g_ref["w"]),
                rtol=1e-4, atol=1e-5,
            )

    def test_stays_sharded_end_to_end(self, devices8):
        mesh = make_mesh({"tp": 8}, devices=devices8)
        X, y, _ = generate_wide_logistic_data(64, 64)
        tp = TensorParallelLogistic(X, y, mesh=mesh)
        # the design matrix is column-sharded, never replicated
        assert not tp.X.sharding.is_fully_replicated
        p = tp.init_params()
        assert not p["w"].sharding.is_fully_replicated
        _, g = tp.logp_and_grad(p)
        # each device owns its coefficient block's gradient
        assert not g["w"].sharding.is_fully_replicated

    def test_indivisible_features_rejected(self, devices8):
        mesh = make_mesh({"tp": 8}, devices=devices8)
        X, y, _ = generate_wide_logistic_data(32, 12)
        with pytest.raises(ValueError, match="not divisible"):
            TensorParallelLogistic(X, y, mesh=mesh)

    def test_map_recovers_coefficients(self, devices8):
        mesh = make_mesh({"tp": 8}, devices=devices8)
        X, y, w_true = generate_wide_logistic_data(2048, 16, seed=5)
        tp = TensorParallelLogistic(X, y, mesh=mesh, prior_scale=10.0)
        est = tp.find_map(num_steps=1500, learning_rate=0.05)
        w_est = np.asarray(est["w"])
        # logistic MAP on 2k obs: direction and rough scale recovered
        corr = np.corrcoef(w_est, w_true)[0, 1]
        assert corr > 0.8


class TestExpertParallel:
    def test_matches_unsharded(self, devices8):
        mesh = make_mesh({"experts": 4}, devices=devices8[:4])
        y, _ = generate_expert_mixture_data(256)
        ep = ExpertShardedMixture(y, 8, mesh=mesh)
        ref = ExpertShardedMixture(y, 8)
        p_ep = ep.init_params()
        p_ref = ref.init_params()
        for shift in (0.0, 0.1):
            pe = jax.tree_util.tree_map(lambda a: a + shift, p_ep)
            pr = jax.tree_util.tree_map(lambda a: a + shift, p_ref)
            np.testing.assert_allclose(
                float(ep.logp(pe)), float(ref.logp(pr)), rtol=2e-5
            )
            _, g_ep = ep.logp_and_grad(pe)
            _, g_ref = ref.logp_and_grad(pr)
            for k in g_ref:
                np.testing.assert_allclose(
                    np.asarray(g_ep[k]), np.asarray(g_ref[k]),
                    rtol=1e-4, atol=1e-5,
                )

    def test_component_params_stay_sharded(self, devices8):
        mesh = make_mesh({"experts": 8}, devices=devices8)
        y, _ = generate_expert_mixture_data(128)
        ep = ExpertShardedMixture(y, 16, mesh=mesh)
        p = ep.init_params()
        assert not p["mu"].sharding.is_fully_replicated

    def test_indivisible_components_rejected(self, devices8):
        mesh = make_mesh({"experts": 8}, devices=devices8)
        y, _ = generate_expert_mixture_data(64)
        with pytest.raises(ValueError, match="not divisible"):
            ExpertShardedMixture(y, 6, mesh=mesh)

    def test_map_finds_components(self, devices8):
        mesh = make_mesh({"experts": 4}, devices=devices8[:4])
        y, truth = generate_expert_mixture_data(1024, seed=29)
        ep = ExpertShardedMixture(y, 4, mesh=mesh)
        est = ep.find_map(num_steps=2000, learning_rate=0.05)
        mu_est = np.sort(np.asarray(est["mu"]))
        np.testing.assert_allclose(
            mu_est, np.sort(truth["mu"]), atol=0.5
        )


class TestTensorParallel2D:
    def test_rows_and_columns_composed(self, devices8):
        """2-D {shards x tp} mesh: X tiled over BOTH axes (each device
        holds one (n/2, d/4) tile), y row-sharded, w column-sharded —
        and the posterior still matches the unsharded build."""
        mesh = make_mesh({"shards": 2, "tp": 4}, devices=devices8)
        X, y, _ = generate_wide_logistic_data(128, 64, seed=3)
        tp2 = TensorParallelLogistic(
            X, y, mesh=mesh, rows_axis="shards"
        )
        ref = TensorParallelLogistic(X, y)
        pt = jax.tree_util.tree_map(
            lambda a: a + 0.2, tp2.init_params()
        )
        pr = jax.tree_util.tree_map(
            lambda a: a + 0.2, ref.init_params()
        )
        np.testing.assert_allclose(
            float(tp2.logp(pt)), float(ref.logp(pr)), rtol=2e-5
        )
        _, g2 = tp2.logp_and_grad(pt)
        _, gr = ref.logp_and_grad(pr)
        np.testing.assert_allclose(
            np.asarray(g2["w"]), np.asarray(gr["w"]), rtol=1e-4,
            atol=1e-5,
        )
        # X is tiled over both axes, not just one
        assert not tp2.X.sharding.is_fully_replicated
        assert tp2.X.sharding.shard_shape(tp2.X.shape) == (64, 16)


class TestParallelProperties:
    """Property-style sweeps: equality with the unsharded build must
    hold across the shape space, not just the hand-picked cases."""

    @pytest.mark.parametrize("n,d", [(8, 8), (33, 16), (64, 24), (5, 48)])
    def test_tp_equality_across_shapes(self, devices8, n, d):
        mesh = make_mesh({"tp": 8}, devices=devices8)
        X, y, _ = generate_wide_logistic_data(n, d, seed=n * d)
        tp = TensorParallelLogistic(X, y, mesh=mesh)
        ref = TensorParallelLogistic(X, y)
        pt = jax.tree_util.tree_map(
            lambda a: a + 0.1, tp.init_params()
        )
        pr = jax.tree_util.tree_map(
            lambda a: a + 0.1, ref.init_params()
        )
        np.testing.assert_allclose(
            float(tp.logp(pt)), float(ref.logp(pr)), rtol=5e-5
        )
        _, gt = tp.logp_and_grad(pt)
        _, gr = ref.logp_and_grad(pr)
        np.testing.assert_allclose(
            np.asarray(gt["w"]), np.asarray(gr["w"]), rtol=2e-4,
            atol=1e-5,
        )

    @pytest.mark.parametrize("n_obs,k,n_dev", [
        (17, 8, 2), (64, 12, 4), (9, 16, 8), (128, 8, 8),
    ])
    def test_ep_equality_across_shapes(self, devices8, n_obs, k, n_dev):
        mesh = make_mesh({"experts": n_dev}, devices=devices8[:n_dev])
        y, _ = generate_expert_mixture_data(n_obs, seed=n_obs + k)
        ep = ExpertShardedMixture(y, k, mesh=mesh)
        ref = ExpertShardedMixture(y, k)
        pe = jax.tree_util.tree_map(
            lambda a: a + 0.05, ep.init_params()
        )
        pr = jax.tree_util.tree_map(
            lambda a: a + 0.05, ref.init_params()
        )
        np.testing.assert_allclose(
            float(ep.logp(pe)), float(ref.logp(pr)), rtol=5e-5
        )
        _, ge = ep.logp_and_grad(pe)
        _, gr2 = ref.logp_and_grad(pr)
        for key_ in gr2:
            np.testing.assert_allclose(
                np.asarray(ge[key_]), np.asarray(gr2[key_]),
                rtol=2e-4, atol=1e-5,
            )
