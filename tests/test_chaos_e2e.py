"""ISSUE 5 acceptance e2e: chaos across a REAL process boundary.

1. A mid-frame ``stall`` injected into a live driver↔node exchange
   makes the watchdog produce an incident bundle that is fully
   self-describing: the matching ``fault.stall`` flight-recorder event
   (plan id + trace id), the driver-side span of the stalled operation
   and the node-side spans of the SAME trace id, and the embedded
   :class:`FaultPlan` with live counters — while the system itself
   survives (every request still gets its correct reply once the
   bounded stall ends).
2. ``PFTPU_FAULT_PLAN`` activates a plan in a subprocess node with
   zero code changes — the cross-process lane.
3. A short ``tools/chaos_run.py`` sweep (the invariant checker the
   nightly job runs at ``--seeds 25``) passes end to end.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from pytensor_federated_tpu import faultinject as fi
from pytensor_federated_tpu import telemetry
from pytensor_federated_tpu.telemetry import flightrec, reunion, watchdog
from pytensor_federated_tpu.telemetry import spans as tspans

HERE = os.path.dirname(os.path.abspath(__file__))
NODE = os.path.join(HERE, "chaos_node_proc.py")
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clean_telemetry(tmp_path, monkeypatch):
    monkeypatch.setenv("PFTPU_INCIDENT_DIR", str(tmp_path / "incidents"))
    monkeypatch.setenv("PFTPU_WATCHDOG_MIN_BUNDLE_GAP_S", "0")
    prev = tspans.set_enabled(True)
    prev_rec = flightrec.set_enabled(True)
    telemetry.clear_traces()
    flightrec.clear()
    reunion.clear()
    fi.uninstall()
    yield
    fi.uninstall()
    tspans.set_enabled(prev)
    flightrec.set_enabled(prev_rec)
    telemetry.clear_traces()
    flightrec.clear()
    reunion.clear()


def _spawn_node(extra_env=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, NODE],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    line = proc.stdout.readline().strip()
    assert line.startswith("PORT "), line
    return proc, int(line.split()[1])


@pytest.mark.slow
def test_midframe_stall_yields_self_describing_bundle(monkeypatch):
    """The acceptance scenario: request 2 of a pipelined window stalls
    MID-FRAME (half its bytes sent, then a 4 s pause) while crossing
    the process boundary to a live node; the armed watchdog fires at
    1 s and the bundle it writes must show what chaos did AND how the
    system reacted — then the stall ends and every reply arrives."""
    from pytensor_federated_tpu.service.tcp import TcpArraysClient

    monkeypatch.setenv("PFTPU_WATCHDOG_RPC_S", "1.0")
    plan = fi.FaultPlan(
        [
            fi.FaultRule(
                "stall", point="tcp.send", nth=2, stall_s=4.0,
                cut_frac=0.5,
            )
        ],
        seed=42,
        plan_id="e2e-stall",
    )
    proc, port = _spawn_node()
    try:
        fi.install(plan)
        client = TcpArraysClient("127.0.0.1", port, retries=0)
        before = watchdog.last_incident_path()
        t0 = time.perf_counter()
        # window=1: request 1's reply (carrying the node's span tree
        # for THIS trace) is consumed before request 2's frame stalls.
        results = client.evaluate_many(
            [(np.full(2, float(i)),) for i in range(3)],
            window=1,
            batch=False,
        )
        wall = time.perf_counter() - t0
    finally:
        fi.uninstall()
        proc.kill()
        proc.wait(timeout=30)

    # The system SURVIVED the stall: bounded, and every reply correct.
    assert wall >= 4.0
    for i, out in enumerate(results):
        np.testing.assert_array_equal(out[0], 2.0 * np.full(2, float(i)))

    # The driver's trace id for the stalled operation.
    root = next(
        t
        for t in reversed(telemetry.recent_traces())
        if t["name"] == "rpc.evaluate_many"
    )
    tid = root["trace_id"]

    # The watchdog fired DURING the stall and wrote the bundle.
    bundle_path = watchdog.last_incident_path()
    assert bundle_path and bundle_path != before, (
        "watchdog never produced an incident bundle mid-stall"
    )
    with open(bundle_path, "r", encoding="utf-8") as fh:
        bundle = json.load(fh)
    assert bundle["reason"] == "watchdog:tcp.batch_window"

    # 1) the matching fault.* event, carrying plan id AND trace id
    fault_events = [
        e for e in bundle["flightrec"] if e["kind"] == "fault.stall"
    ]
    assert fault_events, "the injected stall left no fault.* event"
    assert fault_events[0]["plan"] == "e2e-stall"
    assert fault_events[0]["point"] == "tcp.send"
    assert fault_events[0]["trace_id"] == tid

    # 2) driver + node spans for the SAME trace id: the driver's
    # still-open rpc.evaluate_many span is pinned into the flight
    # record; the node's completed node.evaluate tree (request 1's
    # piggyback, across the process boundary) sits in the reunion.
    opens = [
        e
        for e in bundle["flightrec"]
        if e["kind"] == "span.open"
        and e.get("name") == "rpc.evaluate_many"
        and e.get("trace_id") == tid
    ]
    assert opens, "driver-side span of the stalled operation missing"
    merged = {tr["trace_id"]: tr for tr in bundle["trace_reunion"]}
    assert tid in merged, "stalled trace missing from the reunion"
    remote_names = {t["name"] for t in merged[tid]["remote"]}
    assert "node.evaluate" in remote_names, (
        "node-side spans for the stalled trace missing from the bundle"
    )

    # 3) the embedded fault plan with live counters
    assert bundle["fault_plan"]["plan_id"] == "e2e-stall"
    (rule,) = bundle["fault_plan"]["rules"]
    assert rule["kind"] == "stall" and rule["fires"] == 1


def test_env_plan_reaches_subprocess_node():
    """Cross-process activation: the node's rules fire in the NODE
    process (its 2nd compute errors in-band), with zero code changes —
    only PFTPU_FAULT_PLAN in its environment."""
    from pytensor_federated_tpu.service.tcp import (
        RemoteComputeError,
        TcpArraysClient,
    )

    node_plan = fi.FaultPlan(
        [
            fi.FaultRule(
                "compute_error", point="server.compute", nth=2,
                error="chaos crossed the boundary",
            )
        ],
        seed=7,
    )
    proc, port = _spawn_node({"PFTPU_FAULT_PLAN": node_plan.to_json()})
    try:
        client = TcpArraysClient("127.0.0.1", port, retries=0)
        out = client.evaluate(np.arange(3.0))
        np.testing.assert_array_equal(out[0], 2.0 * np.arange(3.0))
        with pytest.raises(
            RemoteComputeError, match="chaos crossed the boundary"
        ):
            client.evaluate(np.arange(3.0))
        out = client.evaluate(np.ones(2))  # nth=2 exhausted
        np.testing.assert_array_equal(out[0], 2.0 * np.ones(2))
        client.close()
    finally:
        proc.kill()
        proc.wait(timeout=30)


@pytest.mark.slow
def test_chaos_run_smoke_slice():
    """The CI smoke slice of the nightly invariant sweep: a few seeds
    on each transport must satisfy every invariant."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    for extra in (["--seeds", "2", "--base-seed", "100"],
                  ["--seeds", "1", "--transport", "tcp"]):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "chaos_run.py"),
             *extra],
            env=env,
            capture_output=True,
            text=True,
            timeout=420,
        )
        assert out.returncode == 0, (
            f"chaos_run {extra} failed:\n{out.stdout}\n{out.stderr}"
        )
        assert '"failures": 0' in out.stdout
