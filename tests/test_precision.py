"""True-f32 contraction policy (precision.py).

The target hardware computes plain f32 contractions at bf16-level
accuracy (measured ~1.4e-3 relerr on a 512-term dot, tools/diag_tpu.out
— the reference never faces this: its exchange dtype is de-facto
float64, reference common.py).  These tests verify the mitigation
MECHANISM on CPU by simulating the chip: a base_dot that rounds
operands to bf16 before multiplying (f32 accumulate) reproduces the
measured error; the 6-pass bf16x3 split over that same degraded primitive must
recover true-f32 accuracy.  On-chip verification of the same recipe is
tools/diag_tpu.py section 1b.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytensor_federated_tpu.precision import (
    POLICIES,
    matmul_precision_ctx,
    pdot,
    resolve_policy,
    split_dot,
    wrap_policy,
)


def _sim_bf16_dot(a, b):
    """The chip's measured behavior: operands rounded to bf16, products
    accumulated in f32."""
    return jnp.matmul(
        a.astype(jnp.bfloat16),
        b.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )


def _relerr(x, ref):
    """Norm-relative error.  Elementwise max-relerr is the WRONG gate
    here: individual outputs of a random 512-dot can nearly cancel
    (measured: plain f32 CPU maxes at 6e-4 relerr on an output whose
    |ref| is 1.6e-3) — the L2 ratio separates honest f32 (~1e-7) from
    bf16-degraded (~1e-3) unambiguously."""
    x = np.asarray(x, np.float64)
    return float(np.linalg.norm(x - ref) / np.linalg.norm(ref))


@pytest.fixture(scope="module")
def mat_vec():
    rng = np.random.default_rng(0)
    A = rng.normal(size=(2048, 512)).astype(np.float32)
    w = rng.normal(size=(512,)).astype(np.float32)
    ref = A.astype(np.float64) @ w.astype(np.float64)
    return jnp.asarray(A), jnp.asarray(w), ref


class TestSplitDot:
    def test_simulated_chip_reproduces_the_trap(self, mat_vec):
        """The simulated bf16 backend must actually be broken (~1e-3),
        else the recovery test below tests nothing."""
        A, w, ref = mat_vec
        err = _relerr(jax.jit(_sim_bf16_dot)(A, w), ref)
        assert err > 1e-4, f"bf16 sim unexpectedly accurate: {err:.3e}"

    def test_split_recovers_true_f32_on_simulated_chip(self, mat_vec):
        """The acceptance line from the round-3 verdict: relerr <= 1e-5
        on the dot that measures ~1.4e-3 un-mitigated — demonstrated
        against the SAME degraded primitive the chip implements."""
        A, w, ref = mat_vec
        out = jax.jit(
            lambda a, b: split_dot(a, b, base_dot=_sim_bf16_dot)
        )(A, w)
        assert _relerr(out, ref) <= 1e-5

    def test_split_matches_plain_f32_on_cpu(self, mat_vec):
        A, w, ref = mat_vec
        out = jax.jit(split_dot)(A, w)
        assert _relerr(out, ref) <= 1e-5

    def test_split_matmul_shapes(self):
        rng = np.random.default_rng(1)
        a = jnp.asarray(rng.normal(size=(8, 16, 32)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(8, 32, 4)).astype(np.float32))
        out = split_dot(a, b)
        ref = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
        assert out.shape == (8, 16, 4)
        assert _relerr(out, ref) <= 1e-5

    def test_gradients_flow(self, mat_vec):
        A, w, _ = mat_vec

        def loss(w_):
            return jnp.sum(split_dot(A, w_) ** 2)

        g = jax.jit(jax.grad(loss))(w)
        g_ref = jax.jit(jax.grad(lambda w_: jnp.sum((A @ w_) ** 2)))(w)
        assert np.isfinite(np.asarray(g)).all()
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(g_ref), rtol=1e-4, atol=1e-2
        )


class TestPolicyRouting:
    def test_resolve_rejects_unknown(self):
        with pytest.raises(ValueError, match="unknown f32 policy"):
            resolve_policy("fastest")

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("PFTPU_F32_POLICY", "split")
        assert resolve_policy(None) == "split"
        monkeypatch.setenv("PFTPU_F32_POLICY", "bogus")
        with pytest.raises(ValueError):
            resolve_policy(None)
        monkeypatch.delenv("PFTPU_F32_POLICY")
        assert resolve_policy(None) == "default"

    @pytest.mark.parametrize("policy", POLICIES)
    def test_all_policies_accurate_on_cpu(self, policy, mat_vec):
        A, w, ref = mat_vec
        out = jax.jit(lambda a, b: pdot(a, b, policy))(A, w)
        assert _relerr(out, ref) <= 1e-5

    def test_env_governs_model_construction(self, monkeypatch):
        """PFTPU_F32_POLICY must flip a whole model coherently: the
        constructor consults the env ONCE and one concrete policy
        flows to every contraction site (review finding: a "default"
        string default left kernel-internal sites re-reading the env
        per trace while the rest stayed plain)."""
        from pytensor_federated_tpu.models.gp import (
            FederatedExactGP,
            generate_gp_data,
        )

        data, _ = generate_gp_data(2, n_obs=16, seed=7)
        monkeypatch.setenv("PFTPU_F32_POLICY", "strict")
        m = FederatedExactGP(data)
        assert m.f32_policy == "strict"
        monkeypatch.delenv("PFTPU_F32_POLICY")
        # ...and the already-built model keeps its resolved policy.
        assert m.f32_policy == "strict"
        assert FederatedExactGP(data).f32_policy == "default"

    def test_wrap_policy_identity_for_default(self):
        fn = lambda x: x  # noqa: E731
        assert wrap_policy(fn, "default") is fn
        assert wrap_policy(fn, "split") is fn
        assert wrap_policy(fn, "strict") is not fn

    def test_ctx_types(self):
        from contextlib import nullcontext

        assert isinstance(matmul_precision_ctx("default"), nullcontext)
        assert isinstance(matmul_precision_ctx("split"), nullcontext)
        assert not isinstance(matmul_precision_ctx("strict"), nullcontext)


class TestModelWiring:
    """On CPU every policy must agree with the default (f32 is true f32
    here); the point is that the strict paths trace, run, differentiate,
    and change nothing when the hardware is honest."""

    def test_exact_gp_strict(self):
        from pytensor_federated_tpu.models.gp import (
            FederatedExactGP,
            generate_gp_data,
        )

        data, _ = generate_gp_data(4, n_obs=64, seed=2)
        base = FederatedExactGP(data)
        strict = FederatedExactGP(data, f32_policy="strict")
        p = base.init_params()
        v0, g0 = base.logp_and_grad(p)
        v1, g1 = strict.logp_and_grad(p)
        np.testing.assert_allclose(float(v0), float(v1), rtol=1e-5)
        for k in g0:
            np.testing.assert_allclose(
                np.asarray(g0[k]), np.asarray(g1[k]), rtol=1e-3, atol=1e-5
            )

    def test_exact_gp_strict_ard(self):
        """2-D ARD inputs exercise the kernel cross-term pdot branch."""
        from pytensor_federated_tpu.models.gp import FederatedExactGP
        from pytensor_federated_tpu.parallel.packing import pack_shards

        rng = np.random.default_rng(3)
        shards = [
            (
                rng.normal(size=(32, 3)).astype(np.float32),
                rng.normal(size=32).astype(np.float32),
            )
            for _ in range(4)
        ]
        data = pack_shards(shards)
        base = FederatedExactGP(data)
        strict = FederatedExactGP(data, f32_policy="strict")
        p = {
            "log_variance": jnp.zeros(()),
            "log_lengthscale": jnp.zeros(3),
            "log_noise": jnp.asarray(-1.0),
        }
        np.testing.assert_allclose(
            float(base.logp(p)), float(strict.logp(p)), rtol=1e-5
        )

    def test_sparse_gp_strict(self):
        from pytensor_federated_tpu.models.gp import (
            FederatedSparseGP,
            generate_gp_data,
        )

        data, pool = generate_gp_data(4, n_obs=64, seed=4)
        z = np.linspace(-2, 2, 16).astype(np.float32)
        base = FederatedSparseGP(data, z)
        strict = FederatedSparseGP(data, z, f32_policy="strict")
        p = base.init_params()
        v0, g0 = base.logp_and_grad(p)
        v1, g1 = strict.logp_and_grad(p)
        np.testing.assert_allclose(float(v0), float(v1), rtol=1e-5)
        for k in g0:
            # Looser than the exact-GP case: the VFE trace residual is
            # a cancellation of two O(n·var) quantities, so ~1e-6
            # relative reordering differences in v amplify to ~1e-3 in
            # the lengthscale gradient — conditioning, not mechanism.
            np.testing.assert_allclose(
                np.asarray(g0[k]), np.asarray(g1[k]), rtol=5e-3, atol=1e-5
            )

    def test_gp_posterior_strict(self):
        from pytensor_federated_tpu.models.gp import (
            FederatedExactGP,
            generate_gp_data,
        )

        data, _ = generate_gp_data(4, n_obs=32, seed=5)
        base = FederatedExactGP(data)
        strict = FederatedExactGP(data, f32_policy="strict")
        p = base.init_params()
        xs = np.linspace(-2, 2, 7).astype(np.float32)
        m0, v0 = base.posterior(p, xs)
        m1, v1 = strict.posterior(p, xs)
        np.testing.assert_allclose(
            np.asarray(m0), np.asarray(m1), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(v0), np.asarray(v1), rtol=1e-4, atol=1e-5
        )

    def test_kalman_strict(self):
        from pytensor_federated_tpu.models.statespace import (
            generate_lgssm_data,
            kalman_logp_parallel,
            kalman_logp_seq,
        )

        y, p = generate_lgssm_data(T=256)
        for fn in (kalman_logp_seq, kalman_logp_parallel):
            v0 = float(jax.jit(lambda q: fn(q, y))(p))
            v1 = float(
                jax.jit(lambda q: fn(q, y, precision="strict"))(p)
            )
            np.testing.assert_allclose(v0, v1, rtol=1e-5)

    def test_kalman_smoothers_forecast_em_strict(self):
        """Every state-space entry point honors precision= (review
        finding: the smoothers/forecast/EM run the same scan
        compositions that degenerated on chip)."""
        from pytensor_federated_tpu.models.statespace import (
            generate_lgssm_data,
            kalman_forecast,
            kalman_smoother_parallel,
            kalman_smoother_seq,
            kalman_smoother_with_lag1,
            lgssm_em,
            panel_em,
        )

        y, p = generate_lgssm_data(T=64)
        for fn in (kalman_smoother_seq, kalman_smoother_parallel):
            m0_, P0_ = fn(p, y)
            m1_, P1_ = fn(p, y, precision="strict")
            np.testing.assert_allclose(
                np.asarray(m0_), np.asarray(m1_), rtol=1e-5, atol=1e-6
            )
            np.testing.assert_allclose(
                np.asarray(P0_), np.asarray(P1_), rtol=1e-5, atol=1e-6
            )
        a = kalman_smoother_with_lag1(p, y, precision="strict")
        b = kalman_smoother_with_lag1(p, y)
        for x0, x1 in zip(a, b):
            np.testing.assert_allclose(
                np.asarray(x0), np.asarray(x1), rtol=1e-5, atol=1e-6
            )
        f0 = kalman_forecast(p, y, 4)
        f1 = kalman_forecast(p, y, 4, precision="strict")
        for x0, x1 in zip(f0, f1):
            np.testing.assert_allclose(
                np.asarray(x0), np.asarray(x1), rtol=1e-5, atol=1e-6
            )
        p0, h0 = lgssm_em(p, y, num_iters=2)
        p1, h1 = lgssm_em(p, y, num_iters=2, precision="strict")
        np.testing.assert_allclose(
            np.asarray(h0), np.asarray(h1), rtol=1e-4
        )
        ys = np.stack([np.asarray(y), np.asarray(y) * 0.9])
        _, hp = panel_em(p, ys, num_iters=2, precision="strict")
        assert np.isfinite(np.asarray(hp)).all()

    def test_linear_predictor_strict(self):
        from pytensor_federated_tpu.models.hierbase import linear_predictor

        rng = np.random.default_rng(6)
        X = jnp.asarray(rng.normal(size=(128, 16)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=16).astype(np.float32))
        out0 = linear_predictor(X, w, 0.5)
        out1 = linear_predictor(X, w, 0.5, compute_dtype="float32_strict")
        np.testing.assert_allclose(
            np.asarray(out0), np.asarray(out1), rtol=1e-5, atol=1e-6
        )

    def test_logistic_model_strict_dtype(self):
        from pytensor_federated_tpu.models.logistic import (
            FederatedLogisticRegression,
            generate_logistic_data,
        )

        data, _ = generate_logistic_data(
            n_shards=4, n_obs=32, n_features=8
        )
        base = FederatedLogisticRegression(data)
        strict = FederatedLogisticRegression(
            data, compute_dtype="float32_strict"
        )
        p = base.init_params()
        np.testing.assert_allclose(
            float(base.logp(p)), float(strict.logp(p)), rtol=1e-5
        )
