"""Property tests for the tenant-id wire field (ISSUE 12).

One field, four implementations — npwire flag bit 32, npproto
extension field 19, the shm doorbell flag bit 8, and the C++ node
(covered in test_native_node.py) — all declared first in
service/wire_registry.py.  The pins:

- round-trip: a stamped tenant reads back exactly via the peek
  readers on every codec, for any unicode id;
- byte-identical: NO tenant => byte-identical frames on every codec
  (the deadline field's property, extended);
- forward-compat: the OFFICIAL protobuf runtime parsing under the
  reference schema skips field 19 (proto3 unknown-field rule);
- loud-failure: a truncated tenant block raises WireError, never a
  silent mis-parse.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from pytensor_federated_tpu.service import shm as shm_mod  # noqa: E402
from pytensor_federated_tpu.service.npproto_codec import (  # noqa: E402
    decode_arrays_msg_full,
    decode_batch_msg,
    encode_arrays_msg,
    encode_batch_msg,
    peek_tenant_msg,
)
from pytensor_federated_tpu.service.npwire import (  # noqa: E402
    WireError,
    decode_arrays_all,
    decode_batch,
    encode_arrays,
    encode_batch,
    peek_deadline,
    peek_tenant,
)

_PROP = settings(max_examples=60, deadline=None)

# Non-empty unicode ids (empty is rejected loudly: absent and empty
# must stay distinguishable on the wire).
_tenants = st.text(min_size=1, max_size=48)

_arrays = st.lists(
    st.integers(min_value=0, max_value=255), min_size=0, max_size=8
).map(lambda xs: np.asarray(xs, dtype=np.float32))


class TestNpwireTenant:
    @_PROP
    @given(arr=_arrays, tenant=_tenants)
    def test_roundtrip_and_peek(self, arr, tenant):
        buf = encode_arrays([arr], uuid=b"u" * 16, tenant=tenant)
        assert peek_tenant(buf) == tenant
        arrays, uuid, error, _tid, _sp = decode_arrays_all(buf)
        assert uuid == b"u" * 16 and error is None
        np.testing.assert_array_equal(arrays[0], arr)

    @_PROP
    @given(
        arr=_arrays,
        tenant=_tenants,
        deadline=st.one_of(
            st.none(), st.floats(0.001, 100.0, allow_nan=False)
        ),
    )
    def test_tenant_composes_with_deadline(self, arr, tenant, deadline):
        buf = encode_arrays(
            [arr], uuid=b"u" * 16, tenant=tenant, deadline_s=deadline,
            trace_id=b"t" * 16,
        )
        assert peek_tenant(buf) == tenant
        if deadline is None:
            assert peek_deadline(buf) is None
        else:
            assert peek_deadline(buf) == pytest.approx(deadline)
        decode_arrays_all(buf)  # must stay decodable

    @_PROP
    @given(arr=_arrays)
    def test_no_tenant_byte_identical(self, arr):
        assert encode_arrays([arr], uuid=b"u" * 16) == encode_arrays(
            [arr], uuid=b"u" * 16, tenant=None
        )

    @_PROP
    @given(arr=_arrays, tenant=_tenants)
    def test_batch_roundtrip(self, arr, tenant):
        item = encode_arrays([arr], uuid=b"i" * 16, tenant=tenant)
        buf = encode_batch([item], uuid=b"b" * 16, tenant=tenant)
        assert peek_tenant(buf) == tenant
        items, uuid, error, _tid, _sp = decode_batch(buf)
        assert uuid == b"b" * 16 and error is None
        assert items == [item]
        assert encode_batch([item], uuid=b"b" * 16) == encode_batch(
            [item], uuid=b"b" * 16, tenant=None
        )

    @_PROP
    @given(arr=_arrays, tenant=_tenants, cut=st.integers(1, 64))
    def test_truncation_loud(self, arr, tenant, cut):
        """Any cut INSIDE a tenant-stamped frame raises WireError (or
        the peek succeeds because the cut fell past the block) — never
        another exception, never silence."""
        buf = encode_arrays([arr], uuid=b"u" * 16, tenant=tenant)
        prefix = buf[: max(0, len(buf) - cut)]
        try:
            peek_tenant(prefix)
        except WireError:
            pass
        try:
            decode_arrays_all(prefix)
        except WireError:
            return
        # A successful decode means the cut only removed payload the
        # decoder legitimately tolerated — nothing silent happened.

    def test_empty_tenant_rejected(self):
        with pytest.raises(WireError):
            encode_arrays([], uuid=b"u" * 16, tenant="")

    def test_oversized_tenant_rejected(self):
        with pytest.raises(WireError):
            encode_arrays([], uuid=b"u" * 16, tenant="x" * 70_000)


class TestNpprotoTenant:
    @_PROP
    @given(arr=_arrays, tenant=_tenants)
    def test_roundtrip_and_peek(self, arr, tenant):
        buf = encode_arrays_msg([arr], "uu", tenant=tenant)
        assert peek_tenant_msg(buf) == tenant
        arrays, uuid, error, _tid, _sp = decode_arrays_msg_full(buf)
        assert uuid == "uu" and error is None
        np.testing.assert_array_equal(arrays[0], arr)

    @_PROP
    @given(arr=_arrays)
    def test_no_tenant_byte_identical(self, arr):
        assert encode_arrays_msg([arr], "uu") == encode_arrays_msg(
            [arr], "uu", tenant=None
        )

    @_PROP
    @given(arr=_arrays, tenant=_tenants)
    def test_batch_roundtrip(self, arr, tenant):
        item = encode_arrays_msg([arr], "ii", tenant=tenant)
        buf = encode_batch_msg([item], "bb", tenant=tenant)
        assert peek_tenant_msg(buf) == tenant
        items, uuid, _tid, _sp = decode_batch_msg(buf)
        assert uuid == "bb" and items == [item]

    @_PROP
    @given(arr=_arrays, tenant=_tenants)
    def test_reference_runtime_skips_field_19(self, arr, tenant):
        """The OFFICIAL protobuf runtime parsing under the reference
        schema (no field 19) must skip the tenant id by wire type —
        the same forward-compatibility pin fields 14-18 carry."""
        from test_npproto_codec import _official_messages

        _nd, InputArrays, _gl = _official_messages()
        buf = encode_arrays_msg([arr], "uu", tenant=tenant)
        msg = InputArrays()
        msg.ParseFromString(buf)
        assert msg.uuid == "uu"
        assert len(msg.items) == 1


class TestShmTenant:
    @_PROP
    @given(tenant=_tenants, body=st.binary(max_size=32))
    def test_roundtrip_and_peek(self, tenant, body):
        frame = shm_mod.encode_frame(
            shm_mod._KIND_EVAL, b"u" * 16, body, tenant=tenant,
            deadline_s=1.5, trace_id=b"t" * 16,
        )
        assert shm_mod.frame_tenant(frame) == tenant
        kind, uuid, error, tid, deadline_s, _part, _ver, off, buf = (
            shm_mod.decode_frame(frame)
        )
        assert kind == shm_mod._KIND_EVAL and error is None
        assert deadline_s == pytest.approx(1.5)
        assert buf[off:] == body  # the tenant block never eats body bytes

    @_PROP
    @given(body=st.binary(max_size=32))
    def test_no_tenant_byte_identical(self, body):
        a = shm_mod.encode_frame(shm_mod._KIND_EVAL, b"u" * 16, body)
        b = shm_mod.encode_frame(
            shm_mod._KIND_EVAL, b"u" * 16, body, tenant=None
        )
        assert a == b
        assert shm_mod.frame_tenant(a) is None

    def test_truncated_tenant_block_loud(self):
        frame = shm_mod.encode_frame(
            shm_mod._KIND_EVAL, b"u" * 16, b"", tenant="acme"
        )
        with pytest.raises(WireError):
            shm_mod.decode_frame(frame[:-3])

    def test_empty_tenant_rejected(self):
        with pytest.raises(WireError):
            shm_mod.encode_frame(
                shm_mod._KIND_EVAL, b"u" * 16, b"", tenant=""
            )
