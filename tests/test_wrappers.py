"""Signature/wrapper contract tests (reference: common.py:12-49 behavior)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytensor_federated_tpu import (
    logp_grad_from_logp,
    spec_of,
    wrap_logp_fn,
    wrap_logp_grad_fn,
)


def quadratic_logp(x, y):
    return -jnp.sum(x**2) - jnp.sum(y**2)


def quadratic_logp_grad(x, y):
    return quadratic_logp(x, y), (-2 * x, -2 * y)


def test_spec_of():
    s = spec_of(np.zeros((2, 3), np.float32), 1.0)
    assert s[0].shape == (2, 3)
    assert s[1].shape == ()


def test_wrap_logp_fn():
    fn = wrap_logp_fn(quadratic_logp)
    (out,) = fn(jnp.array([1.0, 2.0]), jnp.array(3.0))
    np.testing.assert_allclose(out, -14.0)


def test_wrap_logp_fn_rejects_nonscalar():
    fn = wrap_logp_fn(lambda x: x)  # identity: not scalar for vector input
    with pytest.raises(ValueError, match="scalar"):
        fn(jnp.array([1.0, 2.0]))


def test_wrap_logp_grad_fn():
    fn = wrap_logp_grad_fn(quadratic_logp_grad)
    x, y = jnp.array([1.0, 2.0]), jnp.array(3.0)
    logp, gx, gy = fn(x, y)
    np.testing.assert_allclose(logp, -14.0)
    np.testing.assert_allclose(gx, [-2.0, -4.0])
    np.testing.assert_allclose(gy, -6.0)


def test_wrap_logp_grad_fn_arity_mismatch():
    fn = wrap_logp_grad_fn(lambda x, y: (quadratic_logp(x, y), (-2 * x,)))
    with pytest.raises(ValueError, match="one gradient per input"):
        fn(jnp.ones(2), jnp.ones(2))


def test_wrap_logp_grad_fn_shape_mismatch():
    fn = wrap_logp_grad_fn(
        lambda x: (-jnp.sum(x**2), (jnp.zeros((3,)),))
    )
    with pytest.raises(ValueError, match="shape"):
        fn(jnp.ones(2))


def test_logp_grad_from_logp_matches_hand_gradients():
    derived = logp_grad_from_logp(quadratic_logp)
    x, y = jnp.array([1.0, -2.0]), jnp.array(0.5)
    logp_d, (gx_d, gy_d) = derived(x, y)
    logp_h, (gx_h, gy_h) = quadratic_logp_grad(x, y)
    np.testing.assert_allclose(logp_d, logp_h)
    np.testing.assert_allclose(gx_d, gx_h)
    np.testing.assert_allclose(gy_d, gy_h)


def test_wrappers_are_jittable():
    fn = jax.jit(lambda x, y: wrap_logp_grad_fn(quadratic_logp_grad)(x, y))
    out = fn(jnp.ones(2), jnp.array(1.0))
    np.testing.assert_allclose(out[0], -3.0)
