"""Benchmark: federated logp+grad evals/sec, 8-shard Bayesian linear regression.

The BASELINE.json metric.  The reference pays (serialize + 2x network +
Python dispatch) per evaluation — O(ms) per logp+grad call over gRPC
(reference: service.py:150-158); here the whole federated evaluation is
one fused XLA executable, and the benchmark measures *sequential
dependent* evaluations (the way NUTS consumes them: each leapfrog step
feeds the previous gradient forward), chained inside a ``lax.scan`` with
zero host round-trips.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "evals/s", "vs_baseline": N}
``vs_baseline`` is value / 50_000 — the driver-set north-star target for
a v4-16 (BASELINE.json); there is no reference-published number to
compare against (the reference publishes none, BASELINE.md).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

NORTH_STAR = 50_000.0


def main():
    from jax.flatten_util import ravel_pytree

    from pytensor_federated_tpu.models.linear import (
        FederatedLinearRegression,
        generate_node_data,
    )

    data, _ = generate_node_data(8, n_obs=64, seed=123)
    model = FederatedLinearRegression(data)
    params = model.init_params()
    flat0, unravel = ravel_pytree(params)

    def logp_and_grad_flat(x):
        v, g = jax.value_and_grad(lambda x: model.logp(unravel(x)))(x)
        return v, g

    n_evals = 20_000

    @jax.jit
    def chained(x0):
        """Sequential dependent evals — no pipelining tricks: each step
        consumes the previous gradient, like a leapfrog integrator."""

        def body(carry, _):
            x, acc = carry
            v, g = logp_and_grad_flat(x)
            # tiny dependent update keeps the chain honest (not DCE-able)
            x = x + 1e-6 * g
            return (x, acc + v), None

        (x, acc), _ = jax.lax.scan(body, (x0, 0.0), None, length=n_evals)
        return x, acc

    # Warm up / compile.
    out = chained(flat0)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    out = chained(flat0)
    jax.block_until_ready(out)
    wall = time.perf_counter() - t0

    evals_per_sec = n_evals / wall
    print(
        json.dumps(
            {
                "metric": "federated logp+grad evals/sec (8-shard Bayesian "
                "linear regression, sequential dependent chain, zero gRPC)",
                "value": round(evals_per_sec, 1),
                "unit": "evals/s",
                "vs_baseline": round(evals_per_sec / NORTH_STAR, 3),
            }
        )
    )
    print(
        f"# backend={jax.default_backend()} wall={wall:.3f}s n={n_evals}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
