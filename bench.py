"""Benchmark: federated logp+grad evals/sec, 8-shard Bayesian linear regression.

The BASELINE.json metric.  The reference pays (serialize + 2x network +
Python dispatch) per evaluation — O(ms) per logp+grad call over gRPC
(reference: service.py:150-158); here the whole federated evaluation is
one fused XLA executable, and the benchmark measures *sequential
dependent* evaluations (the way NUTS consumes them: each leapfrog step
feeds the previous gradient forward), chained inside a ``lax.scan`` with
zero host round-trips.

Several implementations of the same posterior logp+grad are raced —
XLA autodiff of the model and the sufficient-statistics form (plus a
32x-unrolled chain variant of it) — on a short calibration chain; the
fastest runs the full measurement.  All are asserted to agree
numerically before racing.  The hand-fused Pallas kernel
(ops/pallas_kernels.py) is DEMOTED from the default race (round 4,
docs/performance.md); ``PFTPU_RACE_PALLAS=1`` or the Mosaic settle
pass's ``PFTPU_PALLAS_COMPILED=1`` re-engages it.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "evals/s", "vs_baseline": N}
``vs_baseline`` is value / 50_000 — the driver-set north-star target for
a v4-16 (BASELINE.json); there is no reference-published number to
compare against (the reference publishes none, BASELINE.md).
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

NORTH_STAR = 50_000.0


def preflight(try_mosaic: bool = False) -> bool:
    """One subprocess probe BEFORE this process initializes jax; falls
    back to CPU on a wedged backend so the bench always reports a
    number (see utils.ensure_live_backend for the full policy).
    Returns whether compiled Mosaic may be used for the Pallas path —
    probed only when the caller will actually race it
    (``try_mosaic``), so a default run never pays a Mosaic probe
    compile for a value nothing reads."""
    from pytensor_federated_tpu.utils import ensure_live_backend

    return ensure_live_backend(try_mosaic=try_mosaic)


def make_chained(logp_and_grad_flat, *, unroll: int = 8):
    """Dynamic-length sequential chain: ``chained(x0, n)`` runs ``n``
    dependent evals.  The trip count is a *traced* argument (fori_loop
    lowers to while_loop), so ONE compile serves every chain length —
    on the TPU each distinct static length would otherwise cost a
    20-40 s remote compile per sizing stage.

    The body is manually unrolled ``unroll``x (``lax.fori_loop``'s own
    ``unroll=`` requires static bounds): each while iteration runs
    ``unroll`` *sequential dependent* evals, amortizing the loop's
    per-iteration overhead without breaking the dependence chain —
    numerics are bit-identical to ``unroll=1`` for any ``n`` (a
    remainder loop handles ``n % unroll``)."""

    @jax.jit
    def chained(x0, n):
        """Sequential dependent evals — no pipelining tricks: each step
        consumes the previous gradient, like a leapfrog integrator."""

        def step(carry):
            x, acc = carry
            v, g = logp_and_grad_flat(x)
            # tiny dependent update keeps the chain honest (not DCE-able)
            return (x + 1e-6 * g, acc + v)

        def body_unrolled(_i, carry):
            for _ in range(unroll):
                carry = step(carry)
            return carry

        carry = jax.lax.fori_loop(0, n // unroll, body_unrolled, (x0, 0.0))
        return jax.lax.fori_loop(
            0, n % unroll, lambda _i, c: step(c), carry
        )

    return chained


def time_chain(chained, x0, n, *, warm=True):
    """Wall time of one ``chained(x0, n)`` run.  ``warm=True`` runs once
    first (compile + cache warm); pass ``warm=False`` when the runner's
    executable is already warm from a previous stage."""
    if warm:
        jax.block_until_ready(chained(x0, jnp.asarray(n, jnp.int32)))
    t0 = time.perf_counter()
    out = chained(x0, jnp.asarray(n, jnp.int32))
    jax.block_until_ready(out)
    return time.perf_counter() - t0


class _SkipPallas(Exception):
    """Deliberate skip of the demoted Pallas race — NOT a failure."""


def telemetry_overhead(
    runner, flat0, per_eval_s: float, *, target_wall: float = 0.8,
    n_micro: int = 100_000,
) -> dict:
    """The telemetry subsystem's overhead gate (ISSUE 1 acceptance:
    telemetry-disabled overhead < 2% on the bench driver metric;
    ISSUE 2 extends the same gate to the flight recorder).

    Three measurements, all interleaved best-of-3 so machine-load
    drift and warmth ordering cancel:

    - The DRIVER-METRIC telemetry delta: the winner's warm chained
      executable re-timed with telemetry fully on (flight recorder
      included — the shipping default) vs fully off.  The fused XLA
      chain makes no telemetry calls, so this delta is the true cost
      the subsystem imposes on the headline number — near-zero by
      construction, and this measurement PROVES it stays that way (an
      instrument leaking into the hot path would trip it).
    - The DRIVER-METRIC flight-recorder delta: telemetry on in both
      states, recorder on vs off — isolates the recorder's own span-
      hook cost.  Gated at the same threshold.
    - Micro per-op costs: the RPC-lane pattern (one span + one
      histogram observe) and one flight-recorder event, each state,
      reported for the budget table in docs/observability.md — NOT
      gated against the XLA per-eval time, which is three orders of
      magnitude below the ms-scale RPCs the instruments actually ride.
    """
    from pytensor_federated_tpu.telemetry import flightrec, metrics, spans

    probe = metrics.histogram(
        "pftpu_bench_overhead_probe_seconds",
        "bench.py telemetry-overhead gate probe (not a real latency)",
    )

    def micro_loop() -> float:
        t0 = time.perf_counter()
        for _ in range(n_micro):
            with spans.span("bench.probe"):
                probe.observe(1e-3)
        return (time.perf_counter() - t0) / n_micro

    def micro_record_loop() -> float:
        t0 = time.perf_counter()
        for _ in range(n_micro):
            flightrec.record("bench.probe", v=1)
        return (time.perf_counter() - t0) / n_micro

    n_gate = min(
        max(int(target_wall / max(per_eval_s, 1e-9)), 1_000), 2**31 - 64
    )

    def rate() -> float:
        return n_gate / time_chain(runner, flat0, n_gate, warm=False)

    # Alternate state repetitions and keep each state's BEST rate: a
    # one-shot A-then-B comparison folds machine-load drift (anything
    # else running in the container) and warmth ordering into the
    # delta; best-of-k of interleaved runs cancels both, leaving only
    # a sustained one-sided slowdown — i.e. actual telemetry cost — to
    # trip the gate.
    prev = spans.set_enabled(True)
    prev_rec = flightrec.set_enabled(True)
    rate_on = rate_rec_off = rate_off = 0.0
    micro_on = micro_off = float("inf")
    rec_on = rec_off = float("inf")
    try:
        for _ in range(3):
            spans.set_enabled(True)
            flightrec.set_enabled(True)
            rate_on = max(rate_on, rate())
            micro_on = min(micro_on, micro_loop())
            rec_on = min(rec_on, micro_record_loop())
            flightrec.set_enabled(False)
            rate_rec_off = max(rate_rec_off, rate())
            rec_off = min(rec_off, micro_record_loop())
            spans.set_enabled(False)
            rate_off = max(rate_off, rate())
            micro_off = min(micro_off, micro_loop())
    finally:
        spans.set_enabled(prev)
        flightrec.set_enabled(prev_rec)
        flightrec.clear()
        spans.clear_traces()
    # Fraction of the disabled rate lost when the subsystem is on;
    # clamped at 0 (enabled measuring faster is timing noise).
    delta_frac = max(0.0, 1.0 - rate_on / rate_off)
    rec_delta_frac = max(0.0, 1.0 - rate_on / rate_rec_off)
    return {
        "evals_per_s_enabled": round(rate_on, 1),
        "evals_per_s_disabled": round(rate_off, 1),
        "evals_per_s_flightrec_off": round(rate_rec_off, 1),
        "driver_delta_frac": round(delta_frac, 6),
        "flightrec_delta_frac": round(rec_delta_frac, 6),
        "span_ns_enabled": round(micro_on * 1e9, 1),
        "span_ns_disabled": round(micro_off * 1e9, 1),
        "record_ns_enabled": round(rec_on * 1e9, 1),
        "record_ns_disabled": round(rec_off * 1e9, 1),
        "pass": bool(delta_frac < 0.02 and rec_delta_frac < 0.02),
    }


def collector_overhead(
    runner, flat0, per_eval_s: float, *, target_wall: float = 0.8,
    cadence_s: float = 0.25,
) -> dict:
    """Driver-metric gate for the fleet collector (ISSUE 11
    acceptance: a background fleet-scrape cadence must cost < 2% on
    the bench driver metric — same posture as the telemetry/flightrec
    gates).

    The winner's warm chained executable is re-timed with a
    :class:`~pytensor_federated_tpu.telemetry.collector.FleetCollector`
    sweeping a LIVE exposition endpoint of this very process at a
    250 ms cadence (4-8x the 1-2 s production cadence) versus no
    collector at all.  The cadence is picked from the measured sweep
    cost, not hope: one loopback HTTP self-scrape costs ~2.4 ms of
    GIL time in this container (snapshot JSON both ways), so the
    honest steady-state driver tax is ~1% at 250 ms — a pathological
    regression (a sweep that balloons or blocks the driver) blows the
    2% line, while a 20 ms cadence would fail the gate STRUCTURALLY
    (2.4/20 = 12%) on any machine and measure nothing but itself.
    Interleaved best-of-3 like the sibling gates so machine-load
    drift cancels; the gate also demands the collector actually swept
    (a collector that silently never ran would pass vacuously).
    Never hangs: the scrape lane is loopback HTTP with a bounded
    timeout, and stop() joins with a deadline.
    """
    from pytensor_federated_tpu.telemetry import start_exporter
    from pytensor_federated_tpu.telemetry.collector import FleetCollector

    n_gate = min(
        max(int(target_wall / max(per_eval_s, 1e-9)), 1_000), 2**31 - 64
    )

    def rate() -> float:
        return n_gate / time_chain(runner, flat0, n_gate, warm=False)

    exporter = start_exporter("127.0.0.1", 0)
    rate_on = rate_off = 0.0
    n_sweeps = 0
    try:
        for _ in range(3):
            collector = FleetCollector(
                http_targets=[("127.0.0.1", exporter.port)],
                interval_s=cadence_s,
                timeout_s=1.0,
            ).start()
            try:
                rate_on = max(rate_on, rate())
            finally:
                collector.stop()
            n_sweeps += len(collector.history)
            rate_off = max(rate_off, rate())
    finally:
        exporter.close()
    delta_frac = max(0.0, 1.0 - rate_on / rate_off)
    return {
        "evals_per_s_collector_on": round(rate_on, 1),
        "evals_per_s_collector_off": round(rate_off, 1),
        "driver_delta_frac": round(delta_frac, 6),
        "sweeps_during_gate": n_sweeps,
        "cadence_s": cadence_s,
        "pass": bool(delta_frac < 0.02 and n_sweeps > 0),
    }


def batcher_overhead(n_calls: int = 3_000) -> dict:
    """Idle-latency gate for the server-side micro-batcher (ISSUE 3
    acceptance: a lone request must dispatch immediately — zero
    coalescing wait when idle).

    Measures the per-call cost of routing a single sequential request
    through ``MicroBatcher.submit`` on an otherwise-idle batcher
    against calling the compute directly on the loop (the pre-batching
    ``inline_compute`` path it replaced).  Sequential single calls are
    exactly the idle case: the queue is empty at every submit, so the
    adaptive policy must never sleep.  Interleaved best-of-3, like the
    telemetry gate, so machine-load drift cancels.

    The gate passes while the added latency stays under 75 us/call —
    well under one unit of the ~110-120 us grpc.aio transport floor
    (docs/performance.md), i.e. invisible behind a single real RPC.
    The batched-throughput side is gated in bench_suite config 11
    (batched lane >= 2x the non-batched pipelined lane).
    """
    import asyncio

    from pytensor_federated_tpu.service.batching import MicroBatcher

    x = np.zeros(4, np.float32)

    def compute(a):
        return [a]

    batcher = MicroBatcher(
        compute, None, max_batch=32, max_wait_us=200.0, inline=True
    )

    async def batched_per_call() -> float:
        t0 = time.perf_counter()
        for _ in range(n_calls):
            await batcher.submit((x,))
        return (time.perf_counter() - t0) / n_calls

    async def direct_per_call() -> float:
        t0 = time.perf_counter()
        for _ in range(n_calls):
            compute(x)
        return (time.perf_counter() - t0) / n_calls

    async def measure():
        batched = direct = float("inf")
        for _ in range(3):
            batched = min(batched, await batched_per_call())
            direct = min(direct, await direct_per_call())
        return batched, direct

    batched_s, direct_s = asyncio.run(measure())
    delta_us = max(0.0, (batched_s - direct_s) * 1e6)
    return {
        "idle_submit_us": round(batched_s * 1e6, 2),
        "direct_call_us": round(direct_s * 1e6, 2),
        "idle_delta_us": round(delta_us, 2),
        "pass": bool(delta_us < 75.0),
    }


def faultinject_overhead(n_guard: int = 200_000, n_wire: int = 4_000) -> dict:
    """Disabled-path cost gate for the fault-injection shims (ISSUE 5
    acceptance: with no plan installed, the shims must be
    indistinguishable from the pre-chaos build).

    With no plan, every shim is ``if runtime.active_plan is not None``
    — one module-attribute load.  Two measurements, best-of-3
    interleaved like the other gates:

    - ``guard_ns``: the no-plan check itself, measured in a tight loop
      (the exact expression the shims execute).
    - ``wire_roundtrip_us``: one npwire encode+decode of a small frame
      (the hot path that carries the most shims), with the shims in
      place and no plan.

    The gate PASSES when the projected per-RPC shim cost — the guard
    executed at every wired-in choke point an RPC crosses (client
    encode/send/recv/decode + server recv/decode/compute/encode/send
    ≈ 10 sites) — stays under 1% of the ~110 us grpc.aio transport
    floor every real RPC pays (docs/performance.md "Host lane
    budget"); the codec round-trip is reported alongside for scale.
    An if-check that got accidentally expensive (e.g. a property call
    or an import in the hot path) trips it.
    """
    from pytensor_federated_tpu.faultinject import runtime as fi_rt
    from pytensor_federated_tpu.service.npwire import (
        decode_arrays_all,
        encode_arrays,
    )

    if fi_rt.active_plan is not None:  # the gate measures the OFF path
        fi_rt.uninstall()
    x = np.zeros(8, np.float32)

    def guard_loop() -> float:
        t0 = time.perf_counter()
        for _ in range(n_guard):
            if fi_rt.active_plan is not None:  # the shims' exact guard
                raise AssertionError("unreachable")
        return (time.perf_counter() - t0) / n_guard

    def wire_loop() -> float:
        t0 = time.perf_counter()
        for _ in range(n_wire):
            decode_arrays_all(encode_arrays([x], uuid=b"b" * 16))
        return (time.perf_counter() - t0) / n_wire

    guard_s = wire_s = float("inf")
    for _ in range(3):
        guard_s = min(guard_s, guard_loop())
        wire_s = min(wire_s, wire_loop())
    shim_sites_per_rpc = 10
    rpc_floor_s = 110e-6  # grpc.aio per-call floor, docs/performance.md
    overhead_frac = (guard_s * shim_sites_per_rpc) / rpc_floor_s
    return {
        "guard_ns": round(guard_s * 1e9, 2),
        "wire_roundtrip_us": round(wire_s * 1e6, 2),
        "shim_sites_per_rpc": shim_sites_per_rpc,
        "overhead_frac_of_rpc_floor": round(overhead_frac, 6),
        "pass": bool(overhead_frac < 0.01 and guard_s < 1e-6),
    }


def deadline_overhead(n_check: int = 200_000, n_wire: int = 4_000) -> dict:
    """Disabled-path cost gate for deadline propagation (ISSUE 10
    acceptance: with no deadline bound, the machinery must be
    indistinguishable from the pre-deadline build — same shape as the
    ``faultinject_overhead`` gate).

    With no ambient deadline, the whole per-call cost is ONE
    contextvar read on the encode path (``deadline.wire_budget``) plus
    a flag test per decode; the wire stays byte-identical.  Two
    measurements, best-of-3 interleaved like the other gates:

    - ``check_ns``: ``wire_budget()`` with no deadline bound — the
      exact expression every client encode executes.
    - ``wire_roundtrip_us`` / ``wire_deadline_us``: one npwire
      encode+decode of a small frame without and WITH a deadline
      stamped, so the enabled-path field cost is visible alongside.

    PASSES when the projected per-RPC cost of the disabled path — the
    check at the ~4 deadline-aware choke points an RPC crosses
    (client encode + bounded read, server admission peek + scope
    bind) — stays under 1% of the ~110 us grpc.aio floor
    (docs/performance.md "Host lane budget").
    """
    from pytensor_federated_tpu.service import deadline as dl
    from pytensor_federated_tpu.service.npwire import (
        decode_arrays_all,
        encode_arrays,
        peek_deadline,
    )

    assert dl.remaining_s() is None  # the gate measures the OFF path
    x = np.zeros(8, np.float32)

    def check_loop() -> float:
        t0 = time.perf_counter()
        for _ in range(n_check):
            if dl.wire_budget() is not None:  # the clients' exact guard
                raise AssertionError("unreachable")
        return (time.perf_counter() - t0) / n_check

    def wire_loop(deadline_s) -> float:
        t0 = time.perf_counter()
        for _ in range(n_wire):
            buf = encode_arrays(
                [x], uuid=b"b" * 16, deadline_s=deadline_s
            )
            peek_deadline(buf)
            decode_arrays_all(buf)
        return (time.perf_counter() - t0) / n_wire

    check_s = wire_s = wire_dl_s = float("inf")
    for _ in range(3):
        check_s = min(check_s, check_loop())
        wire_s = min(wire_s, wire_loop(None))
        wire_dl_s = min(wire_dl_s, wire_loop(5.0))
    check_sites_per_rpc = 4
    rpc_floor_s = 110e-6  # grpc.aio per-call floor, docs/performance.md
    overhead_frac = (check_s * check_sites_per_rpc) / rpc_floor_s
    return {
        "check_ns": round(check_s * 1e9, 2),
        "wire_roundtrip_us": round(wire_s * 1e6, 2),
        "wire_deadline_us": round(wire_dl_s * 1e6, 2),
        "check_sites_per_rpc": check_sites_per_rpc,
        "overhead_frac_of_rpc_floor": round(overhead_frac, 6),
        "pass": bool(overhead_frac < 0.01 and check_s < 2e-6),
    }


def partition_overhead(n_plan: int = 20_000, n_round: int = 2_000) -> dict:
    """Shard/reassemble cost gate for the gradient-partition lane
    (ISSUE 13): the driver-side work a reduce-scatter reply adds on
    top of the wire — plan the partitions, slice a representative
    flat gradient, reassemble it under the full loud-validation rules
    — must stay a small fraction of the ~110 us RPC floor, or the
    bytes saved would be paid back in CPU.

    Two measurements, best-of-3 like the sibling gates:

    - ``plan_ns``: ``plan_partitions(total, 8)`` — the pure shard
      math both ends derive per window.
    - ``roundtrip_us``: slice a 16k-element f64 gradient (128 KiB,
      the production-width shape of suite config 15) into 8 partition
      slices and reassemble them through :class:`Reassembler`
      (every add validates geometry/overlap/dtype; result() checks
      coverage) — the whole driver-side cost of one 8-way reduce
      reply.

    PASSES when one full slice+reassemble round trip stays under 50%
    of the RPC floor (measured ~33 us in this container — it replaces
    EIGHT full-gradient decodes plus their frames, so the ceiling is
    a large net win; the gate exists to catch a validation-path
    regression, not to race memcpy) and the plan alone stays
    sub-microsecond-per-shard."""
    from pytensor_federated_tpu.routing.partition import (
        Reassembler,
        plan_partitions,
    )

    total, count = 16_384, 8
    flat = np.random.default_rng(0).normal(size=total)

    def plan_loop() -> float:
        t0 = time.perf_counter()
        for _ in range(n_plan):
            plan_partitions(total, count)
        return (time.perf_counter() - t0) / n_plan

    def round_loop() -> float:
        t0 = time.perf_counter()
        for _ in range(n_round):
            plan = plan_partitions(total, count)
            r = Reassembler(total, count, flat.dtype)
            for p in plan:
                r.add(p, flat[p.offset : p.offset + p.length])
            r.result()
        return (time.perf_counter() - t0) / n_round

    plan_s = round_s = float("inf")
    for _ in range(3):
        plan_s = min(plan_s, plan_loop())
        round_s = min(round_s, round_loop())
    rpc_floor_s = 110e-6  # docs/performance.md "Host lane budget"
    frac = round_s / rpc_floor_s
    return {
        "plan_ns": round(plan_s * 1e9, 1),
        "roundtrip_us": round(round_s * 1e6, 2),
        "total_elems": total,
        "count": count,
        "roundtrip_frac_of_rpc_floor": round(frac, 4),
        "pass": bool(frac < 0.50 and plan_s < 1e-6 * count),
    }


def linalg_block_overhead(n_hdr: int = 20_000, n_fact: int = 150) -> dict:
    """Driver/store protocol cost gate for the blocked-linalg lane
    (ISSUE 19): what the block-store protocol adds on top of the wire
    and the numeric kernels.  Two measurements, best-of-3 like the
    sibling gates:

    - ``header_ns``: one op-header encode+decode plus one tile-header
      encode+decode (with full geometry validation) — the per-request
      bookkeeping every block-store message pays.
    - ``step_us``: one full right-looking factorization STEP driven
      end-to-end through the in-process store (16x16 f64 in 8-tile
      blocks: CHOL_PANEL dispatch, panel-merge validation, SYRK
      broadcast, every loud check on), kernels included — the
      driver-side critical path between two wire calls.

    INTEGRITY-GATED like the race: every timed factorization is
    checked against ``np.linalg.cholesky`` and the gate fails on any
    drift — a fast wrong factor must never pass.

    PASSES when the header bookkeeping stays under 10% of the ~110 us
    RPC floor (it rides on every message) and a full protocol step
    stays under 5x the floor (the step spans >= 2 RPCs plus the tile
    kernels; the gate catches a validation-path regression, not a
    kernel race)."""
    from pytensor_federated_tpu.linalg import (
        BlockedCholesky,
        BlockLayout,
        LocalBlockClient,
    )
    from pytensor_federated_tpu.linalg.blocks import (
        OPCODES,
        decode_op_header,
        encode_op_header,
    )

    lay = BlockLayout(16, 16, 8, 8)
    a_mat = np.random.default_rng(0).normal(size=(16, 16))
    a_mat = a_mat @ a_mat.T / 16 + np.eye(16)
    ref = np.linalg.cholesky(a_mat)

    def hdr_loop() -> float:
        t0 = time.perf_counter()
        for _ in range(n_hdr):
            decode_op_header(encode_op_header(OPCODES["SYRK_UPDATE"], 1, 2))
            lay.decode_tile_header(lay.encode_tile_header(1, 0))
        return (time.perf_counter() - t0) / n_hdr

    def fact_loop() -> tuple:
        maxerr = 0.0
        t0 = time.perf_counter()
        for _ in range(n_fact):
            l = BlockedCholesky(lay, [LocalBlockClient(lay)]).factor(a_mat)
            maxerr = max(maxerr, float(np.max(np.abs(l - ref))))
        per_step = (time.perf_counter() - t0) / n_fact / lay.grid_rows
        return per_step, maxerr

    hdr_s = step_s = float("inf")
    maxerr = 0.0
    for _ in range(3):
        hdr_s = min(hdr_s, hdr_loop())
        s, e = fact_loop()
        step_s = min(step_s, s)
        maxerr = max(maxerr, e)
    rpc_floor_s = 110e-6  # docs/performance.md "Host lane budget"
    hdr_frac = hdr_s / rpc_floor_s
    return {
        "header_ns": round(hdr_s * 1e9, 1),
        "step_us": round(step_s * 1e6, 2),
        "header_frac_of_rpc_floor": round(hdr_frac, 4),
        "factor_maxerr": maxerr,
        "pass": bool(
            hdr_frac < 0.10
            and step_s < 5 * rpc_floor_s
            and maxerr < 1e-10
        ),
    }


def shm_overhead(n_pings: int = 300) -> dict:
    """Idle gate for the zero-copy shm transport (ISSUE 9): one
    doorbell round-trip with an EMPTY arena write — slot allocate +
    generation stamp + descriptor frame + node-side slot validation +
    reply, no compute.  This is the fixed overhead every shm call pays
    on top of payload copies (which are the lane's whole saving), so
    it must stay bounded and the probe must never hang (in-process
    node thread, bounded connect, socket timeout inherited from
    ``connect_timeout_s``).  Best-of-3 batches like the other gates.

    Pass line: under 1.5 ms — an order of magnitude under the ~15-30
    ms/eval a real federated logp round pays, and generous enough for
    a loaded container (measured ~0.1-0.2 ms idle)."""
    import threading

    from pytensor_federated_tpu.service.shm import (
        ShmArraysClient,
        serve_shm,
    )

    def compute(*arrays):
        return [np.zeros(1, np.float32)]

    ports = []
    threading.Thread(
        target=serve_shm,
        args=(compute,),
        kwargs=dict(ready_callback=ports.append, max_connections=1),
        daemon=True,
    ).start()
    deadline = time.time() + 10.0
    while not ports and time.time() < deadline:
        time.sleep(0.005)
    if not ports:
        raise RuntimeError("shm gate node did not come up")
    client = ShmArraysClient(
        "127.0.0.1", ports[0], connect_timeout_s=5.0
    )
    try:
        client.ping()  # connect + attach + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n_pings):
                client.ping()
            best = min(best, (time.perf_counter() - t0) / n_pings)
    finally:
        client.close()
    rtt_us = best * 1e6
    return {
        "doorbell_rtt_us": round(rtt_us, 2),
        "pass": bool(rtt_us < 1500.0),
    }


def ring_overhead(n_pings: int = 300) -> dict:
    """Idle gate for the zero-syscall ring transport (ISSUE 18): one
    seqlock-ring round-trip with an EMPTY arena write — submission
    record produce + futex wake + node-side seqlock validation +
    completion record + consume, no compute, no socket bytes on the
    descriptor path.  Mirrors ``shm_overhead`` so the two lanes stay
    comparable on the same container.

    Pass line: under 1.5 ms, same parity posture as the doorbell gate.
    On this 1-core container a blocking round trip is context-switch
    bound (~50-60 us, within a few us of the doorbell); the ≤10-15 us
    spin-hit regime needs a genuinely-parallel 2-core colocated pair
    (docs/performance.md "Zero-copy budget").  ``descriptor_syscalls``
    reports the futex/fallback shim counters across the timed pings —
    the zero-syscall claim is about this descriptor path, and in
    lock-step it should stay a small multiple of the ping count
    (park/wake pairs), dropping to ~0 when replies are already
    committed on arrival (pipelined drain)."""
    import threading

    from pytensor_federated_tpu.service.ring import (
        RingArraysClient,
        reset_syscall_counts,
        serve_ring,
        syscall_counts,
    )

    def compute(*arrays):
        return [np.zeros(1, np.float32)]

    ports = []
    threading.Thread(
        target=serve_ring,
        args=(compute,),
        kwargs=dict(ready_callback=ports.append, max_connections=1),
        daemon=True,
    ).start()
    deadline = time.time() + 10.0
    while not ports and time.time() < deadline:
        time.sleep(0.005)
    if not ports:
        raise RuntimeError("ring gate node did not come up")
    client = RingArraysClient(
        "127.0.0.1", ports[0], connect_timeout_s=5.0
    )
    try:
        client.ping()  # connect + attach + warm
        if client._com_ring is None:
            raise RuntimeError("ring gate: attach fell back to doorbell")
        best = float("inf")
        counts = {}
        for _ in range(3):
            reset_syscall_counts()
            t0 = time.perf_counter()
            for _ in range(n_pings):
                client.ping()
            elapsed = (time.perf_counter() - t0) / n_pings
            if elapsed < best:
                best, counts = elapsed, dict(syscall_counts())
    finally:
        client.close()
    rtt_us = best * 1e6
    # Physics floor: a sub-microsecond "round trip" through two
    # seqlock hand-offs plus a compute dispatch did not happen.
    return {
        "ring_rtt_us": round(rtt_us, 2),
        "descriptor_syscalls": counts,
        "n_pings": n_pings,
        "pass": bool(0.5 < rtt_us < 1500.0),
    }


def sharded_update_overhead(n_round: int = 2_000) -> dict:
    """Driver-side cost gate for the ZeRO-style sharded optimizer
    (ISSUE 16): what one sharded step adds on TOP of the wire compared
    with a plain evaluate — the version stamp on every update frame
    and the slice-fold bookkeeping when the replies land.  Must stay a
    small fraction of the ~110 us RPC floor: the lane's win is moving
    optimizer state and gradient bytes off the driver, and a fat
    driver-side fold would hand the savings straight back as CPU.

    Two measurements, best-of-3 like the sibling gates:

    - ``stamp_us``: encode a 16k-element f32 update request WITH the
      partition + version blocks minus the same frame without them —
      the pure wire delta per update request (flag byte, geometry,
      one u64).
    - ``apply_us``: fold 8 applied :class:`ShardResult` update slices
      into the 16k driver parameter vector via
      :meth:`ShardedOptimizer.apply` plus one
      :func:`parse_stale_error` classification — the whole
      driver-side bookkeeping of one 8-owner step.

    PASSES when stamp + apply stays under 50% of the RPC floor."""
    from pytensor_federated_tpu.optim import (
        ShardedOptimizer,
        parse_stale_error,
        stale_message,
    )
    from pytensor_federated_tpu.optim.sharded import ShardResult
    from pytensor_federated_tpu.service.npwire import encode_arrays

    total, count = 16_384, 8

    class _Stub:  # never dialed: apply() is pure driver-side math
        evaluate_versioned = staticmethod(lambda *a, **k: None)

    opt = ShardedOptimizer(total, clients=[_Stub()] * count)
    flat = np.zeros(total, np.float32)
    params = np.random.default_rng(0).normal(size=total).astype(np.float32)
    slices = [
        params[p.offset : p.offset + p.length].copy() for p in opt.parts
    ]
    stale = stale_message(opt.parts[0], holds=3, expected=2)

    def stamp_loop() -> float:
        part = tuple(opt.parts[0])
        t0 = time.perf_counter()
        for i in range(n_round):
            encode_arrays(
                [params], uuid=b"u" * 16, partition=part, version=i
            )
        versioned = (time.perf_counter() - t0) / n_round
        t0 = time.perf_counter()
        for _ in range(n_round):
            encode_arrays([params], uuid=b"u" * 16)
        plain = (time.perf_counter() - t0) / n_round
        return max(0.0, versioned - plain)

    def apply_loop() -> float:
        results = [
            ShardResult(k, "applied", 1, loss=0.0, update=slices[k])
            for k in range(count)
        ]
        t0 = time.perf_counter()
        for _ in range(n_round):
            opt.apply(flat, results)
            parse_stale_error(stale)
        return (time.perf_counter() - t0) / n_round

    stamp_s = apply_s = float("inf")
    for _ in range(3):
        stamp_s = min(stamp_s, stamp_loop())
        apply_s = min(apply_s, apply_loop())
    rpc_floor_s = 110e-6  # docs/performance.md "Host lane budget"
    frac = (stamp_s + apply_s) / rpc_floor_s
    return {
        "stamp_us": round(stamp_s * 1e6, 2),
        "apply_us": round(apply_s * 1e6, 2),
        "total_elems": total,
        "count": count,
        "step_frac_of_rpc_floor": round(frac, 4),
        "pass": bool(frac < 0.50),
    }


def gateway_overhead(n_calls: int = 200) -> dict:
    """Uncontended-path latency gate for the gateway tier (ISSUE 12):
    the same lock-step call measured direct-to-node and through a
    1-replica gateway, interleaved best-of-3 like the sibling gates.
    The gateway adds one asyncio hop, the fairness admission peeks,
    one batch-frame wrap, and one extra localhost round trip — its
    whole job is amortizing those across thousands of connections, so
    the per-call toll on an EMPTY gateway must stay small.

    Pass line: added latency under 2.5 ms/call — an order of magnitude
    under the ~15-30 ms a real federated logp round pays, with
    headroom for a loaded CI container (measured ~0.3-0.8 ms idle)."""
    import threading

    from pytensor_federated_tpu.gateway import GatewayThread
    from pytensor_federated_tpu.routing import NodePool
    from pytensor_federated_tpu.service.tcp import (
        TcpArraysClient,
        serve_tcp_once,
    )

    def compute(*arrays):
        return [np.zeros(1, np.float32)]

    ports = []
    threading.Thread(
        target=serve_tcp_once,
        args=(compute,),
        kwargs=dict(ready_callback=ports.append, concurrent=True),
        daemon=True,
    ).start()
    deadline = time.time() + 10.0
    while not ports and time.time() < deadline:
        time.sleep(0.005)
    if not ports:
        raise RuntimeError("gateway gate node did not come up")
    pool = NodePool([("127.0.0.1", ports[0])], transport="tcp")
    x = np.zeros(8, np.float32)
    direct_s = via_s = float("inf")
    gw = GatewayThread(pool)
    gw.start()
    direct = TcpArraysClient("127.0.0.1", ports[0])
    via = TcpArraysClient("127.0.0.1", gw.port, tenant="gate")
    try:
        direct.evaluate(x)  # warm connects
        via.evaluate(x)
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n_calls):
                direct.evaluate(x)
            direct_s = min(
                direct_s, (time.perf_counter() - t0) / n_calls
            )
            t0 = time.perf_counter()
            for _ in range(n_calls):
                via.evaluate(x)
            via_s = min(via_s, (time.perf_counter() - t0) / n_calls)
    finally:
        via.close()
        direct.close()
        gw.stop()
        pool.close()
    added_us = (via_s - direct_s) * 1e6
    return {
        "direct_call_us": round(direct_s * 1e6, 2),
        "gateway_call_us": round(via_s * 1e6, 2),
        "added_latency_us": round(added_us, 2),
        "pass": bool(added_us < 2500.0),
    }


# Module-level (multiprocessing-spawn needs an importable target): the
# shm-lane node serving THIS benchmark's exact logp+grad — same model,
# same data seed, so the race's numerical-equality gate applies to the
# transport lane unchanged.
def _bench_shm_node(port):
    import logging

    logging.disable(logging.ERROR)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()
    import jax as _jax
    from jax.flatten_util import ravel_pytree as _ravel

    from pytensor_federated_tpu.models.linear import (
        FederatedLinearRegression,
        generate_node_data,
    )

    data, _ = generate_node_data(8, n_obs=64, seed=123)
    model = FederatedLinearRegression(data)
    _flat0, unravel = _ravel(model.init_params())
    fn = _jax.jit(
        lambda x: _jax.value_and_grad(lambda v: model.logp(unravel(v)))(x)
    )

    def compute(x):
        v, g = fn(x)
        return [np.asarray(v), np.asarray(g)]

    from pytensor_federated_tpu.service.shm import serve_shm

    serve_shm(compute, "127.0.0.1", port)


class MeasurementIntegrityError(RuntimeError):
    """A timing the integrity guards refuse to trust (degenerate chain,
    inconsistent stages, physics-impossible rate).  A DEDICATED type so
    callers can distinguish "the measurement is untrustworthy" from a
    real XLA/runtime failure (jax.errors.JaxRuntimeError also
    subclasses RuntimeError; catching that as an integrity trip would
    misdiagnose e.g. a remote-compile outage and retry a fresh compile
    into it)."""


def measure_rate(
    chained,
    flat0,
    *,
    per_eval0: float = None,
    n_cal: int = 2_000,
    floor: int = 20_000,
    mid_wall: float = 1.0,
    target_wall: float = 3.5,
):
    """Steady-state evals/s of a ``make_chained`` runner, with two-stage
    sizing: the short calibration chain is dominated by dispatch/launch
    overhead (on TPU a 2k-step chain reads ~3x slower than steady
    state), so re-measure at ``mid_wall`` seconds using the calibrated
    rate, then size the final chain from the *measured* rate to a
    ``target_wall`` wall — long enough that the loop's amortized
    per-iteration cost, not host dispatch, is what's rated.  Every stage
    reuses the runner's one compiled executable (dynamic trip count).

    ``per_eval0``: optional pre-measured seconds/eval from an earlier
    calibration (bench.py's candidate race) — skips the internal
    calibration stage; the caller must have already run ``chained``
    once (its executable is assumed compiled and warm).

    Shared by bench.py (driver metric) and bench_suite.py so the two
    benchmarks can never diverge in sizing methodology.  Returns
    ``(evals_per_sec, n_evals, wall_seconds)``.

    Integrity guard (added after the first live TPU capture recorded a
    6.8e11 evals/s "rate"): a chain whose gradient is exactly zero or
    non-finite degenerates into a loop-invariant body that XLA hoists,
    so the loop times nothing and the sizing cascade explodes.  Before
    rating, a 2-step chain must show the state actually advancing to a
    finite value — otherwise this raises instead of producing a number
    physics forbids.  Chain lengths are also clamped below int32
    overflow (the trip count is a traced int32).
    """
    from pytensor_federated_tpu.telemetry import flightrec as _flightrec

    _I32_SAFE = 2**31 - 64

    def _refuse(verdict: str, msg: str):
        # Integrity-gate verdicts are flight-recorded (taxonomy:
        # bench.integrity) — a capture session's incident bundle shows
        # WHICH physics gate refused, even after the process moved on.
        _flightrec.record("bench.integrity", verdict=verdict, detail=msg)
        return MeasurementIntegrityError(msg)

    x2, _acc2 = chained(flat0, jnp.asarray(2, jnp.int32))
    x2 = np.asarray(jax.block_until_ready(x2))
    if not np.all(np.isfinite(x2)):
        raise _refuse(
            "degenerate-nonfinite",
            "degenerate chain: state is non-finite after 2 evals — "
            "the eval NaNs on this backend; rating it would time a "
            "constant loop, not the computation",
        )
    if np.array_equal(x2, np.asarray(flat0)):
        raise _refuse(
            "degenerate-zero-grad",
            "degenerate chain: state identical to x0 after 2 evals "
            "(zero gradient) — XLA hoists the loop-invariant body and "
            "the 'rate' would be meaningless",
        )
    if per_eval0 is None:
        per_eval0 = time_chain(chained, flat0, n_cal) / n_cal
    n_mid = min(max(floor, int(mid_wall / max(per_eval0, 1e-9))), _I32_SAFE)
    wall_mid = time_chain(chained, flat0, n_mid, warm=False)
    per_eval = wall_mid / n_mid
    # Stage-consistency guard.  In the first live capture the SAME warm
    # executable went from 15 ms/eval at calibration to ~20 ns/eval at
    # the mid stage (the tunneled runtime stopped executing and
    # returned immediately) and the sizing cascade then "measured"
    # 6.8e11 evals/s.  A 100x stage speedup is impossible once the
    # per-eval cost dwarfs dispatch overhead (~1 ms); below that,
    # dispatch amortization makes huge legitimate ratios, so the guard
    # only applies to slow evals (fast ones are covered by the MFU
    # physics gate and the degenerate-chain check).
    if per_eval0 > 1e-3 and per_eval < per_eval0 / 100.0:
        raise _refuse(
            "stage-inconsistent-mid",
            f"inconsistent timing: {per_eval0 * 1e6:.3g} us/eval at "
            f"calibration but {per_eval * 1e6:.3g} us/eval at the mid "
            "stage — the runtime is returning without executing "
            "(wedged/flaky tunnel?); refusing to record",
        )
    n = min(
        max(n_mid, int(target_wall / max(per_eval, 1e-9))), _I32_SAFE
    )
    if n == n_mid:  # target already met; a re-run would add no information
        _flightrec.record(
            "bench.integrity", verdict="pass", n=n_mid,
            evals_per_s=n_mid / wall_mid,
        )
        return n_mid / wall_mid, n_mid, wall_mid
    wall = time_chain(chained, flat0, n, warm=False)
    rate = n / wall
    if wall < (n * per_eval) / 100.0:
        raise _refuse(
            "stage-inconsistent-final",
            f"inconsistent timing: final chain of {n} evals finished "
            f"{100 * wall / (n * per_eval):.2g}% faster than the mid-"
            "stage rate predicts — runtime returned without executing; "
            "refusing to record",
        )
    _flightrec.record(
        "bench.integrity", verdict="pass", n=n, evals_per_s=rate
    )
    return rate, n, wall


def main():
    # Computed BEFORE the preflight so the Mosaic probe compile only
    # runs when the Pallas race is actually requested.
    race_pallas = (
        os.environ.get("PFTPU_RACE_PALLAS") == "1"
        or os.environ.get("PFTPU_PALLAS_COMPILED") == "1"
    )
    mosaic_ok = preflight(try_mosaic=race_pallas)

    from jax.flatten_util import ravel_pytree

    from pytensor_federated_tpu.models.linear import (
        FederatedLinearRegression,
        generate_node_data,
    )

    data, _ = generate_node_data(8, n_obs=64, seed=123)
    model = FederatedLinearRegression(data)
    params = model.init_params()
    flat0, unravel = ravel_pytree(params)

    def autodiff_flat(x):
        return jax.value_and_grad(lambda x: model.logp(unravel(x)))(x)

    candidates = {"xla-autodiff": autodiff_flat}

    # Sufficient-statistics path: nodes release six stats per shard
    # instead of raw data; the same posterior evaluates in O(1) per
    # shard (models/linear.py: linreg_suffstats).
    model_ss = FederatedLinearRegression(data, use_suffstats=True)

    def suffstat_flat(x):
        return jax.value_and_grad(lambda x: model_ss.logp(unravel(x)))(x)

    candidates["suffstats"] = suffstat_flat

    # Fused Pallas kernel path (same posterior: kernel data-logp with
    # forward-supplied VJP + autodiff prior).  Compiled Mosaic was
    # decided by the preflight probe; the pin works both ways — a
    # failed probe forces interpreter mode even if
    # PFTPU_PALLAS_COMPILED=1 is set, otherwise the opt-in env var
    # would re-select the compiled path the probe just found wedged,
    # and the first kernel call would hang.
    # DEMOTED from the default race (round 4, docs/performance.md):
    # the Pallas kernels never won on any backend and compiled Mosaic
    # never reached a live chip across two rounds of capture attempts,
    # so their per-capture compile cost buys nothing.  They still race
    # when explicitly asked for — PFTPU_RACE_PALLAS=1, or
    # PFTPU_PALLAS_COMPILED=1 (what the automated Mosaic settle pass
    # sets, tools/tpu_capture.py --try-mosaic), so a future live window
    # can still overturn the demotion with a measured win.  A plain
    # skip, NOT a raise into the except below: "unavailable" in the
    # capture tails must keep meaning an actual import/build failure.
    pallas_flat = None
    if not race_pallas:
        print(
            "# pallas demoted from the default race "
            "(PFTPU_RACE_PALLAS=1 re-engages it)",
            file=sys.stderr,
        )
    try:
        if not race_pallas:
            raise _SkipPallas
        from pytensor_federated_tpu.ops.pallas_kernels import linreg_logp_grad_fn

        interpret = not (mosaic_ok and jax.default_backend() == "tpu")
        print(f"# pallas interpret={interpret}", file=sys.stderr)

        (x_d, y_d), mask_d = model.data.tree()
        kern = linreg_logp_grad_fn(x_d, y_d, mask_d, interpret=interpret)

        def pallas_flat(x):
            def full(v):
                p = unravel(v)
                return model.prior_logp(p) + kern.data_logp(p)

            return jax.value_and_grad(full)(x)

    except _SkipPallas:
        pass  # already announced above; "unavailable" = real failures
    except Exception as e:  # pragma: no cover - backend-dependent build
        print(f"# pallas path unavailable: {e}", file=sys.stderr)

    if pallas_flat is not None:
        candidates["pallas-fused"] = pallas_flat

    # Zero-copy shm transport lane (ISSUE 9): the SAME posterior
    # evaluated on a colocated subprocess node over the shared-memory
    # arena transport, raced behind the same equality gate via
    # jax.pure_callback.  It documents what the host lane costs next
    # to the fused on-device chain — it is not expected to win.  CPU
    # backend only: a host callback inside the chain on the tunneled
    # TPU is a wedge risk nothing here needs to take, and the lane it
    # measures is host-side by definition.  Own try: a failure costs
    # only this candidate, never the JSON line.
    shm_client = None
    shm_proc = None
    if jax.default_backend() == "cpu":
        try:
            import multiprocessing as mp
            import socket as _socket

            from pytensor_federated_tpu.service.shm import ShmArraysClient

            with _socket.socket() as _s:
                _s.bind(("127.0.0.1", 0))
                shm_port = _s.getsockname()[1]
            ctx = mp.get_context("spawn")
            shm_proc = ctx.Process(
                target=_bench_shm_node, args=(shm_port,), daemon=True
            )
            shm_proc.start()
            shm_client = ShmArraysClient(
                "127.0.0.1", shm_port,
                connect_timeout_s=2.0, connect_retries=60,
                connect_backoff_s=0.5,
            )
            x0_np = np.asarray(flat0)
            deadline = time.time() + 120.0
            while True:  # node warms (jit compile) behind the connect
                try:
                    shm_client.evaluate(x0_np)
                    break
                except (ConnectionError, OSError):
                    if time.time() > deadline or not shm_proc.is_alive():
                        raise RuntimeError("shm bench node never came up")
                    time.sleep(0.5)

            _shm_out_shapes = (
                jax.ShapeDtypeStruct((), flat0.dtype),
                jax.ShapeDtypeStruct(flat0.shape, flat0.dtype),
            )

            def _shm_cb(xv):
                v, g = shm_client.evaluate(np.asarray(xv))
                return (
                    np.asarray(v, dtype=flat0.dtype),
                    np.asarray(g, dtype=flat0.dtype),
                )

            def shm_flat(x):
                return jax.pure_callback(_shm_cb, _shm_out_shapes, x)

            candidates["shm-node"] = shm_flat
        except Exception as e:
            print(f"# shm lane unavailable: {e}", file=sys.stderr)
            if shm_client is not None:
                shm_client.close()
                shm_client = None
            if shm_proc is not None:
                shm_proc.terminate()
                shm_proc = None
    else:
        print(
            "# shm lane skipped (host lane raced on CPU backend only)",
            file=sys.stderr,
        )

    # Correctness gate before racing — an impl that builds but disagrees
    # numerically must FAIL the bench, not be skipped.  Checked at the
    # origin and at a perturbed point (origin-only can hide slope terms).
    flat1 = flat0 + 0.1 * jnp.arange(flat0.shape[0], dtype=flat0.dtype)
    for probe_pt in (flat0, flat1):
        va, ga = autodiff_flat(probe_pt)
        for name, fn in candidates.items():
            if name == "xla-autodiff":
                continue
            vp, gp = fn(probe_pt)
            np.testing.assert_allclose(float(va), float(vp), rtol=2e-4)
            np.testing.assert_allclose(
                np.asarray(ga), np.asarray(gp), rtol=2e-3, atol=1e-3
            )

    # Calibrate on a short chain, pick the winner.  The measurement can
    # REFUSE (measure_rate's integrity guards: degenerate chain, or a
    # flaky runtime returning without executing) — the CLAUDE.md
    # invariant is that bench.py always prints its one JSON line, so a
    # refusal becomes an explicit zero-value record carrying the reason
    # rather than a traceback with no line.
    try:
        # Known wedge point: a compiled run on the tunneled backend can
        # hang past any reasonable wall (CLAUDE.md) — an armed deadline
        # (opt-in: PFTPU_WATCHDOG_BENCH_S seconds) turns that into an
        # incident bundle a capture session can commit.  The one-JSON-
        # line invariant is untouched: the watchdog only reports.
        from pytensor_federated_tpu.telemetry import watchdog as _watchdog

        # env_timeout_s degrades a garbage knob to the default — the
        # one-JSON-line invariant must not die on a misspelt env var.
        bench_arm = _watchdog.env_timeout_s("PFTPU_WATCHDOG_BENCH_S", 0.0)
        _bench_wd = _watchdog.arm("bench.measure", bench_arm)

        n_cal = 2_000
        runners = {name: make_chained(fn) for name, fn in candidates.items()}
        # Explicit variant -> candidate mapping for FLOP attribution;
        # parsing the label (e.g. splitting on "-u") would silently
        # mis-attribute any future hyphenated impl name.
        variant_base = {name: name for name in candidates}
        # On chip the flagship is launch/loop-bound (~11 us/eval at
        # unroll=8), so the while-loop's per-iteration overhead is a
        # live candidate for the cap: race a 32x-unrolled chain of the
        # historically fastest impl too.  make_chained's unroll is
        # numerics-identical for any n (remainder loop), so no extra
        # equality gate is needed — only one extra compile.
        if "suffstats" in candidates:
            runners["suffstats-u32"] = make_chained(
                candidates["suffstats"], unroll=32
            )
            variant_base["suffstats-u32"] = "suffstats"
        cal = {
            name: time_chain(runner, flat0, n_cal)
            for name, runner in runners.items()
        }
        best = min(cal, key=cal.get)
        for name, t in cal.items():
            print(f"# calib {name}: {n_cal / t:,.0f} evals/s", file=sys.stderr)

        evals_per_sec, n_evals, wall = measure_rate(
            runners[best], flat0, per_eval0=cal[best] / n_cal
        )
        _watchdog.disarm(_bench_wd)
    except RuntimeError as e:
        _watchdog.disarm(_bench_wd)
        print(
            json.dumps(
                {
                    "metric": "federated logp+grad evals/sec (8-shard "
                    "Bayesian linear regression, sequential dependent "
                    "chain, zero gRPC)",
                    "value": 0.0,
                    "unit": "evals/s",
                    "vs_baseline": 0.0,
                    "backend": jax.default_backend(),
                    "error": f"measurement refused: {e}",
                }
            )
        )
        print(f"# measurement refused: {e}", file=sys.stderr)
        return

    # FLOP accounting for the winner AND the generic autodiff path —
    # the suffstats winner compresses the likelihood to O(1) per shard,
    # so its FLOP count must not stand in for the generic path's
    # (round-1 VERDICT) and both are recorded.
    from pytensor_federated_tpu.flopcount import mfu as mfu_fields
    from pytensor_federated_tpu.flopcount import xla_flops_per_eval

    # Unroll variants (e.g. "suffstats-u32") are the SAME eval fn as
    # their base candidate — account FLOPs via the explicit mapping.
    base = variant_base[best]
    flop_extra = mfu_fields(
        xla_flops_per_eval(candidates[base], flat0), evals_per_sec
    )
    if best != "xla-autodiff":
        flop_extra["flops_per_eval_autodiff"] = xla_flops_per_eval(
            autodiff_flat, flat0
        )

    try:
        overhead = telemetry_overhead(runners[best], flat0, wall / n_evals)
    except Exception as e:  # the one-JSON-line invariant outranks the gate
        overhead = {"error": f"{type(e).__name__}: {e}", "pass": False}

    try:
        batcher = batcher_overhead()
    except Exception as e:  # same invariant
        batcher = {"error": f"{type(e).__name__}: {e}", "pass": False}

    try:
        fault_shims = faultinject_overhead()
    except Exception as e:  # same invariant
        fault_shims = {"error": f"{type(e).__name__}: {e}", "pass": False}

    try:
        shm_gate = shm_overhead()
    except Exception as e:  # same invariant
        shm_gate = {"error": f"{type(e).__name__}: {e}", "pass": False}

    try:
        ring_gate = ring_overhead()
    except Exception as e:  # same invariant
        ring_gate = {"error": f"{type(e).__name__}: {e}", "pass": False}

    try:
        deadline_gate = deadline_overhead()
    except Exception as e:  # same invariant
        deadline_gate = {"error": f"{type(e).__name__}: {e}", "pass": False}

    try:
        partition_gate = partition_overhead()
    except Exception as e:  # same invariant
        partition_gate = {
            "error": f"{type(e).__name__}: {e}", "pass": False,
        }

    try:
        collector_gate = collector_overhead(
            runners[best], flat0, wall / n_evals
        )
    except Exception as e:  # same invariant
        collector_gate = {
            "error": f"{type(e).__name__}: {e}", "pass": False,
        }

    try:
        gateway_gate = gateway_overhead()
    except Exception as e:  # same invariant
        gateway_gate = {"error": f"{type(e).__name__}: {e}", "pass": False}

    try:
        sharded_gate = sharded_update_overhead()
    except Exception as e:  # same invariant
        sharded_gate = {"error": f"{type(e).__name__}: {e}", "pass": False}

    try:
        linalg_gate = linalg_block_overhead()
    except Exception as e:  # same invariant
        linalg_gate = {"error": f"{type(e).__name__}: {e}", "pass": False}

    # The shm race lane's node is no longer needed once measurement
    # and gates are done (the gates spin their own in-process node).
    if shm_client is not None:
        try:
            shm_client.close()
        except Exception:
            pass
    if shm_proc is not None:
        shm_proc.terminate()
        shm_proc.join(timeout=5)

    print(
        json.dumps(
            {
                "metric": "federated logp+grad evals/sec (8-shard Bayesian "
                "linear regression, sequential dependent chain, zero gRPC)",
                "value": round(evals_per_sec, 1),
                "unit": "evals/s",
                "vs_baseline": round(evals_per_sec / NORTH_STAR, 3),
                # Honesty fields: which device actually ran (the
                # preflight falls back to CPU on a wedged tunnel) and
                # which racing implementation won.
                "backend": jax.default_backend(),
                "impl": best,
                "telemetry_overhead": overhead,
                "batcher_overhead": batcher,
                "faultinject_overhead": fault_shims,
                "shm_overhead": shm_gate,
                "ring_overhead": ring_gate,
                "deadline_overhead": deadline_gate,
                "partition_overhead": partition_gate,
                "collector_overhead": collector_gate,
                "gateway_overhead": gateway_gate,
                "sharded_update_overhead": sharded_gate,
                "linalg_block_overhead": linalg_gate,
                **flop_extra,
            }
        )
    )
    print(
        f"# backend={jax.default_backend()} impl={best} wall={wall:.3f}s "
        f"n={n_evals}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
