#!/usr/bin/env python
"""Chaos harness: sweep seeded fault plans, assert the system invariants.

For each seed, a :class:`~pytensor_federated_tpu.faultinject.FaultPlan`
is generated (driver-side rules installed in this process; node-side
rules shipped to one subprocess replica via ``PFTPU_FAULT_PLAN`` — the
cross-process activation lane) and a pooled driver runs a realistic
workload against 2-3 subprocess replicas: pipelined windows, single
evaluations, hedged requests, then a recovery phase.  The invariants —
the claims the recovery machinery (watchdog, breakers, hedging,
mid-window failover) makes — are checked every seed:

1. **Exactly one reply** — every request either returns the CORRECT
   value exactly once, or the call fails with a loud, classified error
   (``RemoteComputeError`` / ``WireError`` / uuid-mismatch
   ``RuntimeError`` / transport error).  Never silence, never a wrong
   value, never a duplicate applied twice (positional assignment makes
   duplicates structurally impossible; values are checked against the
   known compute).
2. **No hang** — every call completes within a deadline derived from
   the armed watchdog window; a stall is watchdog-visible and bounded,
   not an open-ended wedge.
3. **Breakers reconverge** — once faults stop (driver plan
   uninstalled, node rules exhausted, killed replicas respawned),
   probe sweeps must return every breaker to ``closed``, and a final
   clean window must deliver every value correctly (a hedged loser or
   chaos-mangled frame that desynchronized a stream would fail this).
4. **Telemetry accounting** — every driver-side fired fault left its
   ``fault.*`` event in the flight recorder (fired counters == event
   count), so incident bundles can always show what chaos did.

A violated invariant writes an incident bundle (with the fault plan
embedded — see ``tools/incident_report.py``), prints the seed and
bundle path, and exits nonzero.  Replay one seed with
``python tools/chaos_run.py --seed N``.

Usage:
    python tools/chaos_run.py --seeds 25          # the nightly sweep
    python tools/chaos_run.py --seeds 3           # the CI smoke slice
    python tools/chaos_run.py --seed 17 -v        # replay one failure
    python tools/chaos_run.py --seeds 5 --transport tcp
"""

from __future__ import annotations

import os

# Environment guards BEFORE any package import (CLAUDE.md: ad-hoc
# drivers must never dial the TPU plugin), inherited by node children.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PFTPU_WATCHDOG_RPC_S", "2.0")
os.environ.setdefault("PFTPU_WATCHDOG_MIN_BUNDLE_GAP_S", "0")

import argparse  # noqa: E402
import asyncio  # noqa: E402
import json  # noqa: E402
import multiprocessing as mp  # noqa: E402
import random  # noqa: E402
import socket  # noqa: E402
import sys  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402

# Runnable from any cwd (and importable by spawn children).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from pytensor_federated_tpu import faultinject as fi  # noqa: E402
from pytensor_federated_tpu import telemetry  # noqa: E402
from pytensor_federated_tpu.telemetry import flightrec  # noqa: E402
from pytensor_federated_tpu.telemetry import reunion  # noqa: E402
from pytensor_federated_tpu.telemetry import spans as tspans  # noqa: E402
from pytensor_federated_tpu.telemetry.watchdog import (  # noqa: E402
    write_incident_bundle,
)

COMPUTE_DELAY_S = 0.004
#: Per-call deadline: the watchdog window plus the largest bounded
#: fault (stall_s) plus generous slack — crossing it means a real hang.
CALL_DEADLINE_S = 60.0


def _expected(i: float) -> float:
    """The node compute's known value for input [i, 5.0]."""
    return -((i - 3.0) ** 2 + 4.0)


# -- subprocess replicas ----------------------------------------------------


def _serve_grpc_node(port: int, delay: float) -> None:
    """Module-level (spawn needs an importable target): the quad
    compute with a small per-call delay so windows are genuinely in
    flight when faults land.  A PFTPU_FAULT_PLAN inherited from the
    parent's env was already activated at package import."""
    import logging
    import time as _time

    import numpy as _np

    logging.disable(logging.ERROR)  # chaos makes nodes loud on purpose

    def compute(x):
        _time.sleep(COMPUTE_DELAY_S if delay is None else delay)
        x = _np.asarray(x)
        return [
            _np.asarray(-_np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    from pytensor_federated_tpu.service import run_node

    run_node(compute, "127.0.0.1", port)


def _serve_tcp_node(port: int, delay: float) -> None:
    import time as _time

    import numpy as _np

    def compute(x):
        _time.sleep(COMPUTE_DELAY_S if delay is None else delay)
        x = _np.asarray(x)
        return [
            _np.asarray(-_np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    # concurrent=True: the pool's health probes open their own
    # connections alongside the driver's held one.
    serve_tcp_once(compute, "127.0.0.1", port, concurrent=True)


def _serve_shm_node(port: int, delay: float) -> None:
    """The zero-copy lane's replica: shm doorbell + arena pair
    (concurrent by default, so pool probes coexist with the driver's
    held connection)."""
    import time as _time

    import numpy as _np

    def compute(x):
        _time.sleep(COMPUTE_DELAY_S if delay is None else delay)
        x = _np.asarray(x)
        return [
            _np.asarray(-_np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    from pytensor_federated_tpu.service.shm import serve_shm

    serve_shm(compute, "127.0.0.1", port)


def _serve_ring_node(port: int, delay: float) -> None:
    """The zero-syscall lane's replica: seqlock rings in the arena,
    doorbell kept as attach channel + fallback (ISSUE 18)."""
    import time as _time

    import numpy as _np

    def compute(x):
        _time.sleep(COMPUTE_DELAY_S if delay is None else delay)
        x = _np.asarray(x)
        return [
            _np.asarray(-_np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    from pytensor_federated_tpu.service.ring import serve_ring

    serve_ring(compute, "127.0.0.1", port)


def _free_ports(n: int) -> list:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _spawn_node(transport: str, port: int, plan_json=None):
    """Start one replica subprocess; node-side fault plans ride the
    environment (PFTPU_FAULT_PLAN) into the child — the cross-process
    activation lane under test."""
    target = {
        "grpc": _serve_grpc_node,
        "tcp": _serve_tcp_node,
        "shm": _serve_shm_node,
        "ring": _serve_ring_node,
    }[transport]
    saved = os.environ.get(fi.runtime.ENV_VAR)
    if plan_json is not None:
        os.environ[fi.runtime.ENV_VAR] = plan_json
    else:
        os.environ.pop(fi.runtime.ENV_VAR, None)
    try:
        ctx = mp.get_context("spawn")
        proc = ctx.Process(target=target, args=(port, None), daemon=True)
        proc.start()
    finally:
        if saved is None:
            os.environ.pop(fi.runtime.ENV_VAR, None)
        else:
            os.environ[fi.runtime.ENV_VAR] = saved
    return proc


async def _wait_nodes_up_async(
    transport: str, ports, timeout: float = 90.0
) -> None:
    if transport == "grpc":
        from pytensor_federated_tpu.service import get_loads_async

        deadline = time.time() + timeout
        while time.time() < deadline:
            loads = await get_loads_async(
                [("127.0.0.1", p) for p in ports], timeout=1.0
            )
            if all(ld is not None for ld in loads):
                return
            await asyncio.sleep(0.2)
        raise TimeoutError(f"nodes on {ports} failed to start")
    # TCP/shm lanes: a fresh connection proves liveness (the shm
    # doorbell is a TCP accept loop too).
    deadline = time.time() + timeout
    pending = set(ports)
    while pending and time.time() < deadline:
        for p in list(pending):
            try:
                with socket.create_connection(("127.0.0.1", p), timeout=1.0):
                    pending.discard(p)
            except OSError:
                await asyncio.sleep(0.2)
    if pending:
        raise TimeoutError(f"nodes on {sorted(pending)} failed to start")


def _wait_nodes_up(transport: str, ports, timeout: float = 90.0) -> None:
    asyncio.run(_wait_nodes_up_async(transport, ports, timeout))


# -- plan generation --------------------------------------------------------

# (kind, kwargs) templates; nth anchors are drawn per seed.  Every
# template is BOUNDED: stalls are finite, drops reset the connection,
# and every rule carries max_fires — chaos that cannot terminate would
# make the no-hang invariant untestable.
def _driver_templates(transport: str):
    if transport == "shm":
        # The zero-copy lane: doorbell byte faults plus the four
        # arena-specific scenarios (ISSUE 9 — corrupt descriptor,
        # truncated slot, stale generation, doorbell disconnect).
        return [
            ("delay", dict(point="shm.send", delay_s=0.02, max_fires=3)),
            ("disconnect", dict(point="shm.send", max_fires=2)),
            ("drop", dict(point="shm.send", max_fires=2)),
            ("corrupt_bytes", dict(point="shm.send", max_fires=1)),
            ("truncate_frame", dict(point="shm.send", max_fires=1)),
            ("disconnect", dict(point="shm.recv", max_fires=1)),
            ("corrupt_bytes", dict(point="shm.decode", max_fires=1)),
            ("corrupt_descriptor",
             dict(point="shm.descriptor", max_fires=1)),
            ("truncate_slot",
             dict(point="shm.arena.write", max_fires=1)),
            ("stale_generation",
             dict(point="shm.arena.write", max_fires=1)),
            ("stall", dict(point="shm.send", stall_s=1.0, max_fires=1)),
            ("drop", dict(point="pool.probe", max_fires=2)),
        ]
    if transport == "ring":
        # The zero-syscall lane (ISSUE 18): faults on the client's
        # descriptor-ring seams — corrupt/truncated submission records
        # fail THEIR reply in-band server-side, a torn/future-lap
        # seqlock record tears the ring down loudly, a swallowed futex
        # wake exercises the park loop's lost-wake guard — plus the
        # doorbell faults the attach/fallback channel inherits from
        # the shm lane.
        return [
            ("delay", dict(point="ring.send", delay_s=0.02, max_fires=3)),
            ("drop", dict(point="ring.send", max_fires=2)),
            ("corrupt_bytes", dict(point="ring.send", max_fires=1)),
            ("truncate_frame", dict(point="ring.send", max_fires=1)),
            ("corrupt_bytes", dict(point="ring.recv", max_fires=1)),
            ("torn_ring_word", dict(point="ring.record", max_fires=1)),
            ("stale_generation", dict(point="ring.record", max_fires=1)),
            ("ring_stall",
             dict(point="ring.wake", stall_s=0.5, max_fires=1)),
            ("disconnect", dict(point="shm.send", max_fires=1)),
            ("drop", dict(point="pool.probe", max_fires=2)),
        ]
    send = "tcp.send" if transport == "tcp" else "grpc.send"
    recv = "tcp.recv" if transport == "tcp" else "grpc.recv"
    return [
        ("delay", dict(point=send, delay_s=0.02, max_fires=3)),
        ("disconnect", dict(point=send, max_fires=2)),
        ("drop", dict(point=send, max_fires=2)),
        ("corrupt_bytes", dict(point=send, max_fires=1)),
        ("truncate_frame", dict(point=send, max_fires=1)),
        ("disconnect", dict(point=recv, max_fires=1)),
        ("truncate_frame", dict(point="npwire.decode", max_fires=1)),
        ("corrupt_bytes", dict(point="npwire.decode", max_fires=1)),
        ("stall", dict(point=send, stall_s=1.0, max_fires=1)),
        ("drop", dict(point="pool.probe", max_fires=2)),
    ]


def _node_templates(transport: str):
    if transport == "shm":
        # Node-side arena faults: the torn-slot and recycled-slot
        # scenarios land on the REPLY write, where only the node can
        # reach the slot it controls.
        return [
            ("compute_error", dict(point="shm.compute", max_fires=1)),
            ("delay", dict(point="shm.compute", delay_s=0.05,
                           max_fires=2)),
            ("stall", dict(point="shm.compute", stall_s=3.0,
                           max_fires=1)),
            ("drop", dict(point="shm.server.send", max_fires=1)),
            ("duplicate_reply", dict(point="shm.server.send",
                                     max_fires=1)),
            ("truncate_frame", dict(point="shm.server.send",
                                    max_fires=1)),
            ("truncate_slot", dict(point="shm.arena.reply",
                                   max_fires=1)),
            ("stale_generation", dict(point="shm.arena.reply",
                                      max_fires=1)),
            ("kill_process", dict(point="shm.compute", max_fires=1)),
        ]
    if transport == "ring":
        # Node-side ring faults: the completion ring's producer is the
        # only writer that can tear ITS records (torn seqlock word,
        # future-lap stamp); a dropped reply is the accept-then-silence
        # scenario the client's bounded recv must classify; SIGKILL
        # mid-compute proves a parked client wakes and classifies a
        # transient instead of hanging.
        return [
            ("compute_error", dict(point="shm.compute", max_fires=1)),
            ("delay", dict(point="shm.compute", delay_s=0.05,
                           max_fires=2)),
            ("stall", dict(point="shm.compute", stall_s=3.0,
                           max_fires=1)),
            ("drop", dict(point="ring.server.send", max_fires=1)),
            ("truncate_frame", dict(point="ring.server.send",
                                    max_fires=1)),
            ("corrupt_bytes", dict(point="ring.server.recv",
                                   max_fires=1)),
            ("torn_ring_word", dict(point="ring.record", max_fires=1)),
            ("stale_generation", dict(point="ring.record", max_fires=1)),
            ("ring_stall",
             dict(point="ring.wake", stall_s=0.5, max_fires=1)),
            ("kill_process", dict(point="shm.compute", max_fires=1)),
        ]
    reply = "tcp.server.send" if transport == "tcp" else "grpc.server.reply"
    rules = [
        ("compute_error", dict(point="server.compute", max_fires=1)),
        ("delay", dict(point="server.compute", delay_s=0.05, max_fires=2)),
        ("stall", dict(point="server.compute", stall_s=3.0, max_fires=1)),
        ("drop", dict(point=reply, max_fires=1)),
        ("duplicate_reply", dict(point=reply, max_fires=1)),
        ("truncate_frame", dict(point=reply, max_fires=1)),
        ("kill_process", dict(point="server.compute", max_fires=1)),
    ]
    if transport == "grpc":
        rules.append(
            ("getload_garbage", dict(point="server.getload", max_fires=2))
        )
    return rules


def generate_plans(seed: int, transport: str, n_requests: int):
    """Seeded (driver_plan, node_plan_json, n_replicas): 1-3 driver
    rules in this process, 0-2 node rules shipped to ONE replica."""
    rng = random.Random(seed)
    n_replicas = rng.choice([2, 3])
    driver_rules = []
    for kind, kw in rng.sample(_driver_templates(transport), rng.randint(1, 3)):
        kw = dict(kw)
        if rng.random() < 0.7:
            kw["nth"] = rng.randint(1, max(2, n_requests // n_replicas))
            kw.pop("max_fires", None)  # nth defaults to one fire
        driver_rules.append(fi.FaultRule(kind, **kw))
    driver_plan = fi.FaultPlan(
        driver_rules, seed=seed, plan_id=f"chaos-{seed}-driver"
    )
    node_plan_json = None
    if rng.random() < 0.8:
        node_rules = []
        for kind, kw in rng.sample(
            _node_templates(transport), rng.randint(1, 2)
        ):
            kw = dict(kw)
            if kind != "getload_garbage" and rng.random() < 0.7:
                kw["nth"] = rng.randint(2, max(3, n_requests))
                kw.pop("max_fires", None)
            node_rules.append(fi.FaultRule(kind, **kw))
        node_plan_json = fi.FaultPlan(
            node_rules, seed=seed, plan_id=f"chaos-{seed}-node"
        ).to_json()
    return driver_plan, node_plan_json, n_replicas


# -- one seed ---------------------------------------------------------------

#: RuntimeError messages the transports raise as their KNOWN loud
#: verdicts (bare RuntimeError is also what an unclassified internal
#: bug looks like — the asyncio.InvalidStateError escape this harness
#: caught was exactly that class — so only these phrasings count).
_LOUD_RUNTIME_MARKERS = (
    "server error:",
    "uuid mismatch",
    "batch reply",
    "does not advertise",
    "does not answer",
    "faultinject[",
    "deadline exceeded",  # DeadlineExceeded: the ISSUE-10 shed class
    "retry budget exhausted",
)


def _is_loud(exc: BaseException) -> bool:
    """Whether ``exc`` is one of the system's CLASSIFIED loud outcomes.
    Anything else escaping a call is an invariant violation, even if it
    happens to be an exception — silence and unclassified internals
    both fail the seed."""
    import grpc

    from pytensor_federated_tpu.service.npwire import WireError
    from pytensor_federated_tpu.service.tcp import RemoteComputeError

    if isinstance(
        exc,
        (
            RemoteComputeError,
            WireError,
            ConnectionError,
            OSError,
            TimeoutError,
            grpc.aio.AioRpcError,
        ),
    ):
        return True
    if isinstance(exc, RuntimeError):
        msg = str(exc)
        return any(m in msg for m in _LOUD_RUNTIME_MARKERS)
    return False


class Violation(Exception):
    pass


async def _run_seed_async(
    seed, transport, procs, ports, driver_plan, victim, has_node_plan, log
):
    from pytensor_federated_tpu.routing import NodePool, PooledArraysClient

    pool = NodePool(
        [("127.0.0.1", p) for p in ports],
        transport=transport,
        breaker_kwargs=dict(
            failure_threshold=2, backoff_s=0.2, jitter_frac=0.1
        ),
        probe_timeout_s=2.0,
    )
    client = PooledArraysClient(pool)
    n_loud = 0

    async def deadline(coro):
        return await asyncio.wait_for(coro, timeout=CALL_DEADLINE_S)

    def check(i, out, where):
        if out is None:
            raise Violation(f"{where}: request {i} silently unreplied")
        got = float(np.asarray(out[0]))
        want = _expected(float(i))
        if not np.isclose(got, want, rtol=1e-6):
            raise Violation(
                f"{where}: request {i} returned {got}, expected {want} "
                "(silent corruption)"
            )

    try:
        # Phase A: pipelined windows under chaos.
        for w in range(3):
            reqs = [
                (np.array([float(i), 5.0], np.float64),) for i in range(12)
            ]
            try:
                results = await deadline(
                    client.evaluate_many_async(reqs, window=6)
                )
            except asyncio.TimeoutError:
                raise Violation(f"window {w}: hang past {CALL_DEADLINE_S}s")
            except Exception as e:
                if not _is_loud(e):
                    raise Violation(
                        f"window {w}: UNCLASSIFIED error escaped "
                        f"({type(e).__name__}: {str(e)[:200]})"
                    )
                n_loud += 1
                log(f"  window {w}: loud error ({type(e).__name__}: "
                    f"{str(e)[:80]})")
            else:
                for i, out in enumerate(results):
                    check(i, out, f"window {w}")

        # Phase B: singles (warm the hedge estimator), then hedged calls.
        for i in range(10):
            try:
                out = await deadline(
                    client.evaluate_async(np.array([float(i), 5.0]))
                )
            except asyncio.TimeoutError:
                raise Violation(f"single {i}: hang past {CALL_DEADLINE_S}s")
            except Exception as e:
                if not _is_loud(e):
                    raise Violation(
                        f"single {i}: UNCLASSIFIED error escaped "
                        f"({type(e).__name__}: {str(e)[:200]})"
                    )
                n_loud += 1
                log(f"  single {i}: loud error ({type(e).__name__})")
            else:
                check(i, out, "single")
        hedged = PooledArraysClient(
            pool, hedge=True, hedge_min_wait_s=0.001
        )
        for i in range(8):
            try:
                out = await deadline(
                    hedged.evaluate_async(np.array([float(i), 5.0]))
                )
            except asyncio.TimeoutError:
                raise Violation(f"hedged {i}: hang past {CALL_DEADLINE_S}s")
            except Exception as e:
                if not _is_loud(e):
                    raise Violation(
                        f"hedged {i}: UNCLASSIFIED error escaped "
                        f"({type(e).__name__}: {str(e)[:200]})"
                    )
                n_loud += 1
                log(f"  hedged {i}: loud error ({type(e).__name__})")
            else:
                check(i, out, "hedged")

        # Phase C: faults stop -> the system must reconverge.  The
        # driver plan is uninstalled; the replica carrying a node-side
        # plan is restarted PLAN-FREE (a rolling restart — its rules
        # may hold un-hit nth anchors that would otherwise fire during
        # the clean phase); killed replicas are respawned.
        fi.uninstall()
        for k, proc in enumerate(procs):
            restart = not proc.is_alive() or (k == victim and has_node_plan)
            if restart:
                if proc.is_alive():
                    proc.terminate()
                    proc.join(timeout=10)
                else:
                    log(f"  replica {k} died (kill_process?): respawning")
                procs[k] = _spawn_node(transport, ports[k], None)
        await _wait_nodes_up_async(transport, ports)
        deadline_t = time.time() + 30.0
        while time.time() < deadline_t:
            await pool.probe_once_async()
            if all(r.breaker.state == "closed" for r in pool.replicas):
                break
            await asyncio.sleep(0.1)
        bad = [
            (r.address, r.breaker.state)
            for r in pool.replicas
            if r.breaker.state != "closed"
        ]
        if bad:
            raise Violation(
                f"breakers never reconverged after faults stopped: {bad}"
            )

        # The clean window: every value correct — a stream desynchronized
        # by a hedged loser or a chaos-mangled frame would fail here.
        reqs = [(np.array([float(i), 5.0], np.float64),) for i in range(12)]
        results = await deadline(client.evaluate_many_async(reqs, window=6))
        for i, out in enumerate(results):
            check(i, out, "clean window")
    finally:
        fi.uninstall()
        pool.close()
    return n_loud


# -- the overload lane (ISSUE 10) -------------------------------------------


def _is_deadline_loud(exc: BaseException) -> bool:
    """Whether ``exc`` is the DEADLINE/shed classification: the in-band
    DeadlineExceeded class, a gRPC DEADLINE_EXCEEDED abort, or the
    client-side bounded-read TimeoutError."""
    import grpc

    from pytensor_federated_tpu.service.deadline import DeadlineExceeded

    if isinstance(exc, DeadlineExceeded):
        return True
    if isinstance(exc, grpc.aio.AioRpcError):
        return exc.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    return isinstance(exc, TimeoutError)


async def _run_overload_async(seed, procs, ports, victim, params, log):
    """2x-oversubscribed clients against a pool with one stalling
    replica.  Invariants (ISSUE 10 acceptance):

    O1 goodput  — at least ``goodput_floor`` of the calls return the
                  known-correct value (the healthy replica plus
                  routing/failover must keep serving under overload);
    O2 loudness — every non-successful call fails with the deadline/
                  shed classification or a classified transport error,
                  inside its budget (no unclassified escapes);
    O3 no hang  — every call settles within CALL_DEADLINE_S;
    O4 budget   — retry/hedge amplification never exceeds the token
                  bucket's contract (granted <= burst + rate x wall);
    O5 reconverge — after the stalling replica is restarted clean,
                  breakers close, the budget refills, and a clean
                  deadline-free window returns every value correctly.
    """
    from pytensor_federated_tpu.routing import (
        NodePool,
        PooledArraysClient,
        RetryBudget,
    )
    from pytensor_federated_tpu.service.deadline import deadline_scope

    budget = RetryBudget(
        rate_per_s=params["budget_rate"], burst=params["budget_burst"],
        name=f"overload-{seed}",
    )
    pool = NodePool(
        [("127.0.0.1", p) for p in ports],
        transport="grpc",
        # Unary lane: N concurrent callers multiplex over HTTP/2.  The
        # lock-step STREAM lane serializes one call at a time per
        # connection by construction, which is the opposite of an
        # oversubscription scenario.
        client_kwargs=dict(use_stream=False),
        breaker_kwargs=dict(
            failure_threshold=3, backoff_s=0.2, jitter_frac=0.1
        ),
        probe_timeout_s=2.0,
        retry_budget=budget,
    )
    client = PooledArraysClient(pool)
    pool.start()  # live probes: routing must see the slow replica's load

    n_ok = 0
    n_deadline = 0
    n_transient = 0
    lock = asyncio.Lock()

    async def one_call(i: float) -> None:
        nonlocal n_ok, n_deadline, n_transient
        try:
            with deadline_scope(params["deadline_s"]):
                out = await asyncio.wait_for(
                    client.evaluate_async(np.array([i, 5.0])),
                    timeout=CALL_DEADLINE_S,
                )
        except asyncio.TimeoutError:
            raise Violation(
                f"overload call {i}: hang past {CALL_DEADLINE_S}s"
            )
        except Exception as e:  # noqa: BLE001 - classified below
            if _is_deadline_loud(e):
                async with lock:
                    n_deadline += 1
            elif _is_loud(e):
                async with lock:
                    n_transient += 1
            else:
                raise Violation(
                    f"overload call {i}: UNCLASSIFIED error escaped "
                    f"({type(e).__name__}: {str(e)[:200]})"
                )
        else:
            got = float(np.asarray(out[0]))
            want = _expected(float(i))
            if not np.isclose(got, want, rtol=1e-6):
                raise Violation(
                    f"overload call {i}: returned {got}, expected "
                    f"{want} (silent corruption)"
                )
            async with lock:
                n_ok += 1

    async def client_task(k: int) -> None:
        for r in range(params["calls_per_client"]):
            await one_call(float((k * 31 + r) % 12))

    t0 = time.time()
    try:
        await asyncio.gather(
            *(client_task(k) for k in range(params["n_clients"]))
        )
        wall = time.time() - t0
        total = params["n_clients"] * params["calls_per_client"]
        goodput = n_ok / total
        log(
            f"  overload: {n_ok}/{total} ok ({goodput:.0%}), "
            f"{n_deadline} deadline-shed, {n_transient} transient, "
            f"wall {wall:.1f}s, budget {budget.snapshot()}"
        )
        # O1: goodput floor.
        if goodput < params["goodput_floor"]:
            raise Violation(
                f"goodput collapsed under overload: {n_ok}/{total} "
                f"({goodput:.0%}) < floor {params['goodput_floor']:.0%}"
            )
        # O4: amplification stayed inside the token bucket's contract.
        max_granted = budget.burst + budget.rate_per_s * wall + 1.0
        if budget.n_granted > max_granted:
            raise Violation(
                f"retry budget overspent: {budget.n_granted} grants > "
                f"{max_granted:.1f} (burst {budget.burst} + "
                f"{budget.rate_per_s}/s x {wall:.1f}s)"
            )

        # O5: load drops, the stalling replica restarts clean ->
        # breakers close, the budget refills, a clean window is exact.
        procs[victim].terminate()
        procs[victim].join(timeout=10)
        procs[victim] = _spawn_node("grpc", ports[victim], None)
        await _wait_nodes_up_async("grpc", ports)
        deadline_t = time.time() + 30.0
        while time.time() < deadline_t:
            await pool.probe_once_async()
            if (
                all(r.breaker.state == "closed" for r in pool.replicas)
                and budget.tokens() >= budget.burst * 0.9
            ):
                break
            await asyncio.sleep(0.1)
        bad = [
            (r.address, r.breaker.state)
            for r in pool.replicas
            if r.breaker.state != "closed"
        ]
        if bad:
            raise Violation(
                f"breakers never reconverged after load dropped: {bad}"
            )
        if budget.tokens() < budget.burst * 0.9:
            raise Violation(
                f"retry budget never refilled after load dropped "
                f"(tokens {budget.tokens():.1f} / burst {budget.burst})"
            )
        reqs = [(np.array([float(i), 5.0], np.float64),) for i in range(12)]
        results = await asyncio.wait_for(
            client.evaluate_many_async(reqs, window=6),
            timeout=CALL_DEADLINE_S,
        )
        for i, out in enumerate(results):
            if out is None:
                raise Violation(f"clean window: request {i} unreplied")
            got = float(np.asarray(out[0]))
            if not np.isclose(got, _expected(float(i)), rtol=1e-6):
                raise Violation(
                    f"clean window: request {i} returned {got}"
                )
    finally:
        pool.close()
    return {
        "ok_calls": n_ok,
        "deadline_shed": n_deadline,
        "transient": n_transient,
    }


def run_overload_seed(seed: int, verbose: bool) -> dict:
    """One overload scenario (``--lane overload``); same result-dict
    contract as :func:`run_seed`."""

    def log(msg):
        if verbose:
            print(msg, flush=True)

    rng = random.Random(seed ^ 0x10AD)
    params = {
        # The stalling replica: every compute takes a seeded
        # uniform[0, slow_s) delay — mostly past the callers' budget.
        "slow_s": rng.uniform(1.5, 2.5),
        "deadline_s": rng.uniform(0.6, 0.9),
        # 2x oversubscription: two replicas, one effectively stalled,
        # and twice as many concurrent clients as live capacity.
        "n_clients": 8,
        "calls_per_client": rng.choice([6, 8]),
        "budget_rate": 4.0,
        "budget_burst": rng.choice([8.0, 12.0]),
        "goodput_floor": 0.4,
    }
    node_plan_json = fi.FaultPlan(
        [
            fi.FaultRule(
                "slow_compute",
                point="server.compute",
                every=1,
                delay_s=params["slow_s"],
            )
        ],
        seed=seed,
        plan_id=f"overload-{seed}-node",
    ).to_json()
    log(f"overload seed {seed}: {params}")
    ports = _free_ports(2)
    victim = random.Random(seed ^ 0x5EED).randrange(2)
    procs = [
        _spawn_node("grpc", p, node_plan_json if k == victim else None)
        for k, p in enumerate(ports)
    ]
    result = {"seed": seed, "transport": "overload", "ok": True}
    try:
        _wait_nodes_up("grpc", ports)
        stats = asyncio.run(
            _run_overload_async(seed, procs, ports, victim, params, log)
        )
        result.update(stats)
    except Exception as e:  # noqa: BLE001 - every failure becomes a record
        result["ok"] = False
        result["error"] = f"{type(e).__name__}: {e}"
        try:
            result["bundle"] = write_incident_bundle(
                "chaos-overload-violation",
                attrs={"seed": seed, "violation": str(e)[:500]},
            )
        except Exception as be:  # pragma: no cover - disk trouble
            result["bundle"] = f"<bundle write failed: {be}>"
    finally:
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs:
            proc.join(timeout=10)
    return result


# -- the collector lane (ISSUE 11) ------------------------------------------


def _fleet_snapshot_consistent(snap) -> "str | None":
    """The torn-aggregate check: every merged counter must equal the
    sum of the FRESH per-replica scrapes' values (a stale replica
    contributes nothing), and merged histogram bucket totals can never
    exceed their counts.  Returns a violation string or None."""
    expect = {}
    for scrape in snap.replicas.values():
        if not scrape.ok:
            continue
        for name, fam in (scrape.metrics or {}).items():
            if fam.get("type") != "counter":
                continue
            for child in fam.get("children", ()):
                key = (
                    name,
                    tuple(sorted((child.get("labels") or {}).items())),
                )
                expect[key] = expect.get(key, 0.0) + float(
                    child.get("value", 0.0)
                )
    for (name, labelkey), want in expect.items():
        got = None
        for child in (snap.merged.get(name) or {}).get("children", ()):
            if (
                tuple(sorted((child.get("labels") or {}).items()))
                == labelkey
            ):
                got = child.get("value")
                break
        if got is None or abs(got - want) > 1e-6:
            return (
                f"torn merge: counter {name}{dict(labelkey)} merged "
                f"{got} != sum-of-fresh {want}"
            )
    for name, fam in snap.merged.items():
        if fam.get("type") != "histogram":
            continue
        for child in fam.get("children", ()):
            if sum(child["buckets"].values()) > child["count"]:
                return (
                    f"torn merge: histogram {name} bucket total "
                    f"exceeds its count"
                )
    return None


def run_collector_seed(seed: int, verbose: bool) -> dict:
    """One collector-under-chaos scenario (``--lane collector``): a
    FleetCollector sweeps a 3-replica grpc pool at a tight seeded
    cadence while the driver keeps calling, the victim replica —
    which may also serve seeded getload garbage — is SIGKILLed
    mid-collection and later restarted.  Invariants:

    K1 no hang   — sweeps, kills, and recovery all settle inside hard
                   deadlines (a dying peer can never wedge the sweep);
    K2 loudness  — the kill surfaces as snapshot staleness AND a
                   ``collector.replica_stale`` flight event within a
                   few sweeps of landing;
    K3 never torn — EVERY snapshot's merged view equals the sum of its
                   fresh per-replica scrapes (stale replicas
                   contribute nothing), checked counter-exact;
    K4 reconverge — after the victim restarts, a complete (stale-free)
                   sweep returns, with clock offsets for every member;
    K5 engine     — the burn engine ingests every snapshot without an
                   exception and never reports a negative window.
    """

    def log(msg):
        if verbose:
            print(msg, flush=True)

    from pytensor_federated_tpu.routing import (
        NodePool,
        PooledArraysClient,
    )
    from pytensor_federated_tpu.telemetry.collector import (
        LOCAL_REPLICA,
        FleetCollector,
    )
    from pytensor_federated_tpu.telemetry.slo import BurnRateEngine, Slo

    rng = random.Random(seed ^ 0xC011)
    params = {
        "interval_s": rng.uniform(0.05, 0.15),
        "garbage_getload": rng.random() < 0.5,
        "kill_after_s": rng.uniform(0.5, 1.2),
        "traffic_pause_s": rng.uniform(0.002, 0.01),
    }
    log(f"collector seed {seed}: {params}")
    tspans.set_enabled(True)
    flightrec.set_enabled(True)
    flightrec.clear()

    node_plan_json = None
    if params["garbage_getload"]:
        # The victim ALSO answers some GetLoad scrapes with garbage:
        # the collector must book those as loud stale verdicts, never
        # crash or merge them.
        node_plan_json = fi.FaultPlan(
            [
                fi.FaultRule(
                    "getload_garbage", point="server.getload", every=3
                )
            ],
            seed=seed,
            plan_id=f"collector-{seed}-node",
        ).to_json()

    ports = _free_ports(3)
    victim = random.Random(seed ^ 0x5EED).randrange(3)
    procs = [
        _spawn_node("grpc", p, node_plan_json if k == victim else None)
        for k, p in enumerate(ports)
    ]
    dead_addr = f"127.0.0.1:{ports[victim]}"
    result = {"seed": seed, "transport": "collector", "ok": True}
    pool = None
    collector = None
    stop_traffic = threading.Event()
    try:
        _wait_nodes_up("grpc", ports)
        pool = NodePool(
            [("127.0.0.1", p) for p in ports],
            policy="round_robin",
            client_kwargs=dict(use_stream=False),
            breaker_kwargs=dict(failure_threshold=2, backoff_s=0.2),
        )
        client = PooledArraysClient(pool)
        snapshots = []
        engine = BurnRateEngine(
            Slo(p99_s=0.25, goodput_min=0.01), windows_s=(5.0,)
        )
        engine_errors = []

        def observer(snap):
            snapshots.append(snap)
            try:
                report = engine.observe(snap)
                for window in report["windows"].values():
                    reqs = window.get("requests")
                    if reqs is not None and reqs < 0:
                        engine_errors.append(
                            f"negative window requests: {reqs}"
                        )
            except Exception as e:  # noqa: BLE001 - K5 verdict
                engine_errors.append(f"{type(e).__name__}: {e}")

        def traffic():
            x = np.array([1.0, 5.0])
            while not stop_traffic.is_set():
                try:
                    client.evaluate(x)
                except Exception:  # noqa: BLE001 - breaker churn is fine
                    pass
                stop_traffic.wait(params["traffic_pause_s"])

        traffic_thread = threading.Thread(target=traffic, daemon=True)
        traffic_thread.start()
        collector = FleetCollector(
            pool=pool,
            interval_s=params["interval_s"],
            timeout_s=1.0,
            observers=[observer],
        ).start()

        time.sleep(params["kill_after_s"])
        procs[victim].kill()  # SIGKILL, racing whatever sweep is live
        procs[victim].join(timeout=10)

        # K2: loud staleness within a bounded number of sweeps.
        deadline_t = time.time() + 30.0
        while time.time() < deadline_t:
            if any(dead_addr in s.stale for s in snapshots[-8:]):
                break
            time.sleep(params["interval_s"])
        else:
            raise Violation(
                f"collector never marked {dead_addr} stale within 30s "
                f"of its SIGKILL"
            )
        if not any(
            e["kind"] == "collector.replica_stale"
            and e.get("replica") == dead_addr
            for e in flightrec.events()
        ):
            raise Violation(
                "no collector.replica_stale flight event for the "
                "killed replica"
            )

        # K4: restart -> a complete sweep with offsets for everyone.
        procs[victim] = _spawn_node("grpc", ports[victim], None)
        _wait_nodes_up("grpc", ports)
        n_before = len(snapshots)
        deadline_t = time.time() + 30.0
        recovered = None
        while time.time() < deadline_t:
            fresh = snapshots[n_before:]
            complete = [s for s in fresh if not s.stale]
            if complete:
                recovered = complete[-1]
                break
            time.sleep(params["interval_s"])
        if recovered is None:
            raise Violation(
                "no complete sweep within 30s of the victim restarting"
            )
        for addr, scrape in recovered.replicas.items():
            if addr != LOCAL_REPLICA and scrape.clock_offset_s is None:
                raise Violation(
                    f"recovered sweep has no clock offset for {addr}"
                )

        stop_traffic.set()
        traffic_thread.join(timeout=10)
        collector.stop()

        # K3: every snapshot taken across the whole scenario — kills,
        # garbage, restarts — merged exactly from its fresh members.
        for snap in snapshots:
            violation = _fleet_snapshot_consistent(snap)
            if violation is not None:
                raise Violation(violation)
        # K5: the engine survived every sweep.
        if engine_errors:
            raise Violation(
                f"burn engine violations: {engine_errors[:3]}"
            )
        result["sweeps"] = len(snapshots)
        result["stale_sweeps"] = sum(1 for s in snapshots if s.stale)
        log(
            f"  collector: {result['sweeps']} sweeps, "
            f"{result['stale_sweeps']} with staleness, engine ok"
        )
    except Exception as e:  # noqa: BLE001 - every failure becomes a record
        result["ok"] = False
        result["error"] = f"{type(e).__name__}: {e}"
        try:
            result["bundle"] = write_incident_bundle(
                "chaos-collector-violation",
                attrs={"seed": seed, "violation": str(e)[:500]},
            )
        except Exception as be:  # pragma: no cover - disk trouble
            result["bundle"] = f"<bundle write failed: {be}>"
    finally:
        stop_traffic.set()
        if collector is not None:
            collector.stop()
        if pool is not None:
            pool.close()
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs:
            proc.join(timeout=10)
        flightrec.clear()
    return result


# -- the gateway lane (ISSUE 12) --------------------------------------------


async def _gw_client_task(
    host, port, calls, tenant, deadline_s, tally, lock,
    start_delay_s=0.0, pipeline=False,
):
    """One downstream client: one held connection, sequential calls —
    the minimal async npwire peer (the harness cannot spend a thread
    per client at 1k clients).  ``pipeline=True`` sends EVERY frame
    before reading any reply (the hog's flood shape; the gateway
    preserves per-connection FIFO so replies still correlate in
    order).  Every outcome is classified into the shared tally; an
    unclassified escape raises Violation."""
    from pytensor_federated_tpu.gateway import is_overload_error
    from pytensor_federated_tpu.service.deadline import is_deadline_error
    from pytensor_federated_tpu.service.npwire import (
        WireError,
        decode_arrays_all,
        encode_arrays,
        fast_uuid,
    )
    import struct as struct_mod

    async def tally_inc(key):
        async with lock:
            tally[tenant][key] = tally[tenant].get(key, 0) + 1

    reader = writer = None
    try:
        if start_delay_s:
            # Mice arrive over a window, not as one synchronized spike
            # — a real population's arrival process; the hog (delay 0)
            # IS the spike.
            await asyncio.sleep(start_delay_s)
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout=CALL_DEADLINE_S
        )
        sent = []  # (input, uid) pairs whose replies are still owed
        if pipeline:
            for i in calls:
                uid = fast_uuid()
                frame = encode_arrays(
                    [np.array([float(i), 5.0])],
                    uuid=uid,
                    tenant=tenant,
                    deadline_s=deadline_s,
                )
                writer.write(struct_mod.pack("<I", len(frame)) + frame)
                sent.append((i, uid))
            await asyncio.wait_for(
                writer.drain(), timeout=CALL_DEADLINE_S
            )
        for step in range(len(calls)):
            if pipeline:
                i, uid = sent[step]
            else:
                i = calls[step]
                uid = fast_uuid()
                frame = encode_arrays(
                    [np.array([float(i), 5.0])],
                    uuid=uid,
                    tenant=tenant,
                    deadline_s=deadline_s,
                )
                writer.write(struct_mod.pack("<I", len(frame)) + frame)
                await asyncio.wait_for(
                    writer.drain(), timeout=CALL_DEADLINE_S
                )
            try:
                hdr = await asyncio.wait_for(
                    reader.readexactly(4), timeout=CALL_DEADLINE_S
                )
                (n,) = struct_mod.unpack("<I", hdr)
                payload = await asyncio.wait_for(
                    reader.readexactly(n), timeout=CALL_DEADLINE_S
                )
            except asyncio.TimeoutError:
                raise Violation(
                    f"gateway call hang past {CALL_DEADLINE_S}s "
                    f"(tenant {tenant})"
                )
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                # The gateway (or our socket) went away mid-call — a
                # classified transport failure, loud by construction.
                await tally_inc("transport")
                return
            try:
                arrays, ruid, error, _tid, _sp = decode_arrays_all(payload)
            except WireError:
                await tally_inc("wire_error")
                return
            if error is not None:
                if is_deadline_error(error):
                    await tally_inc("deadline")
                elif is_overload_error(error):
                    if f"tenant {tenant}" not in error:
                        raise Violation(
                            f"denial without tenant label: {error[:200]}"
                        )
                    await tally_inc("denied")
                else:
                    await tally_inc("upstream_error")
                continue
            if ruid != uid:
                raise Violation(
                    f"gateway reply uuid mismatch (tenant {tenant})"
                )
            got = float(np.asarray(arrays[0]))
            want = _expected(float(i))
            if not np.isclose(got, want, rtol=1e-6):
                raise Violation(
                    f"gateway returned {got}, expected {want} "
                    "(silent corruption)"
                )
            await tally_inc("ok")
    finally:
        if writer is not None:
            try:
                writer.close()
            except Exception:
                pass


async def _run_gateway_async(seed, procs, ports, victim, params, gw,
                             scaler, log):
    """1k downstream clients vs the gateway, one hog tenant, the
    victim replica SIGKILLed and restarted mid-run.  Invariants
    (ISSUE 12 acceptance):

    G1 fairness  — every non-hog tenant keeps its fair share: ok-rate
                   >= ``fair_floor`` despite the hog's flood;
    G2 loudness  — every denial carries the tenant in-band AND in the
                   pftpu_gateway_denials_total labels AND as a
                   ``gateway.denied`` flight event; no unclassified
                   escape (the client task classifies every outcome);
    G3 no hang   — every call settles within CALL_DEADLINE_S;
    G4 converge  — after the flap heals and load stops, the breakers
                   close, the autoscaler drains what it spawned, and a
                   clean window through the gateway is exact.
    """
    tally = {t: {} for t in params["tenants"] + ["hog"]}
    lock = asyncio.Lock()
    host = "127.0.0.1"

    tasks = []
    # The mice: n_clients held connections spread over the tenants,
    # a few sequential calls each.
    for k in range(params["n_clients"]):
        tenant = params["tenants"][k % len(params["tenants"])]
        calls = [(k * 7 + j) % 12 for j in range(params["calls_per_client"])]
        tasks.append(
            _gw_client_task(
                host, gw.port, calls, tenant,
                params["deadline_s"], tally, lock,
                start_delay_s=(k % 97) / 97.0 * params["mice_spread_s"],
            )
        )
    # The hog: a handful of connections PIPELINING floods far past the
    # quota (a lock-step hog would self-throttle on its own replies).
    for k in range(params["hog_conns"]):
        calls = [(k + j) % 12 for j in range(params["hog_calls_per_conn"])]
        tasks.append(
            _gw_client_task(
                host, gw.port, calls, "hog",
                params["deadline_s"], tally, lock,
                pipeline=True,
            )
        )

    async def flapper():
        # The flap: SIGKILL the victim mid-traffic, restart it, let
        # the pool re-probe it back in.
        await asyncio.sleep(params["flap_after_s"])
        procs[victim].kill()
        procs[victim].join(timeout=10)
        log(f"  flapped replica on port {ports[victim]}")
        await asyncio.sleep(params["flap_down_s"])
        procs[victim] = _spawn_node("tcp", ports[victim], None)
        await _wait_nodes_up_async("tcp", [ports[victim]])
        log("  victim restarted")

    t0 = time.time()
    await asyncio.gather(*tasks, flapper())
    wall = time.time() - t0

    totals = {
        t: sum(c.values()) for t, c in tally.items()
    }
    log(f"  tally ({wall:.1f}s): {tally}")

    # G1: per-tenant fairness floor for every non-hog tenant.
    for tenant in params["tenants"]:
        total = totals[tenant]
        ok = tally[tenant].get("ok", 0)
        if total and ok / total < params["fair_floor"]:
            raise Violation(
                f"tenant {tenant} below fair share: {ok}/{total} ok "
                f"({ok / total:.0%} < {params['fair_floor']:.0%})"
            )

    # G2: denials happened (the hog out-ran its quota), and every one
    # is attributable: in-band (checked per call), tenant-labeled in
    # the metric family, and flight-recorded.
    n_denied = sum(c.get("denied", 0) for c in tally.values())
    if n_denied == 0:
        raise Violation("hog never out-ran its quota — lane mis-tuned")
    if tally["hog"].get("denied", 0) == 0:
        raise Violation("denials landed but none on the hog tenant")
    from pytensor_federated_tpu.telemetry.metrics import REGISTRY

    fam = REGISTRY.get("pftpu_gateway_denials_total")
    metric_denied = 0.0
    if fam is not None:
        for key, child in fam._children.items():
            if key[0] == "hog":
                metric_denied += child.value
    if metric_denied == 0:
        raise Violation(
            "no tenant-labeled denial metric for the hog tenant"
        )
    denied_events = [
        e for e in flightrec.events() if e["kind"] == "gateway.denied"
    ]
    if not any(e.get("tenant") == "hog" for e in denied_events):
        raise Violation("no gateway.denied flight event naming the hog")

    # G4: convergence after the flap + load stop.
    deadline_t = time.time() + 30.0
    pool = gw.pool
    while time.time() < deadline_t:
        await pool.probe_once_async()
        breakers_ok = all(
            r.breaker.state == "closed" for r in pool.replicas
        )
        if breakers_ok and not scaler.owned:
            break
        await asyncio.sleep(0.2)
    bad = [
        (r.address, r.breaker.state)
        for r in pool.replicas
        if r.breaker.state != "closed"
    ]
    if bad:
        raise Violation(f"breakers never reconverged after flap: {bad}")
    if scaler.owned:
        raise Violation(
            f"autoscaler never drained its spawned replicas "
            f"({[f'{h}:{p}' for h, p, _ in scaler.owned]})"
        )
    # Clean window: every value exact through the gateway.
    clean = {t: {} for t in ["clean"]}
    await _gw_client_task(
        host, gw.port, list(range(12)), "clean", None, clean, lock
    )
    if clean["clean"].get("ok", 0) != 12:
        raise Violation(f"clean window not exact: {clean}")
    return {
        "ok_calls": sum(c.get("ok", 0) for c in tally.values()),
        "denied": n_denied,
        "hog_denied": tally["hog"].get("denied", 0),
        "transient": sum(c.get("transport", 0) for c in tally.values()),
        "deadline_shed": sum(
            c.get("deadline", 0) for c in tally.values()
        ),
    }


def run_gateway_seed(seed: int, verbose: bool) -> dict:
    """One gateway scenario (``--lane gateway``); same result-dict
    contract as :func:`run_seed`."""

    def log(msg):
        if verbose:
            print(msg, flush=True)

    from pytensor_federated_tpu.gateway import (
        Autoscaler,
        GatewayThread,
        TenantFairness,
    )
    from pytensor_federated_tpu.routing import NodePool

    rng = random.Random(seed ^ 0x6A7E)
    params = {
        "n_clients": 1000,
        "calls_per_client": 2,
        "tenants": [f"t{i}" for i in range(8)],
        "hog_conns": 4,
        "hog_calls_per_conn": rng.choice([150, 200]),
        # Generous per-call budget: the lane tests fairness and the
        # flap, not deadline pressure (the overload lane owns that).
        "deadline_s": 30.0,
        "fair_floor": 0.6,
        "flap_after_s": rng.uniform(0.3, 0.8),
        "flap_down_s": rng.uniform(0.5, 1.0),
        # Mice arrivals spread over this window, so each mouse
        # tenant's rate (~250 calls / spread) sits inside the quota
        # while the hog's zero-delay flood tears through it.
        "mice_spread_s": 2.0,
        "quota_rate_per_s": 200.0,
        "quota_burst": 100.0,
    }
    log(f"gateway seed {seed}: {params}")
    # Metrics mutate only while telemetry is enabled (metrics.py) —
    # and G2 counts tenant-labeled denial metrics.
    tspans.set_enabled(True)
    flightrec.set_enabled(True)
    if flightrec.capacity() < 16384:
        flightrec.set_capacity(16384)
    flightrec.clear()

    ports = _free_ports(2)
    victim = random.Random(seed ^ 0x5EED).randrange(2)
    procs = [_spawn_node("tcp", p, None) for p in ports]
    extra_procs = []
    result = {"seed": seed, "transport": "gateway", "ok": True}
    pool = None
    gw = None
    scaler = None
    try:
        _wait_nodes_up("tcp", ports)
        pool = NodePool(
            [("127.0.0.1", p) for p in ports],
            transport="tcp",
            probe_interval_s=0.3,
            probe_timeout_s=1.0,
            breaker_kwargs=dict(
                failure_threshold=2, backoff_s=0.2, jitter_frac=0.1
            ),
        )
        pool.start()
        fairness = TenantFairness(
            quota_rate_per_s=params["quota_rate_per_s"],
            quota_burst=params["quota_burst"],
            max_backlog_per_tenant=4096,
        )
        gw = GatewayThread(pool, fairness=fairness, frame_items=16)
        gw.start()

        def spawn():
            (port,) = _free_ports(1)
            proc = _spawn_node("tcp", port, None)
            extra_procs.append(proc)
            return ("127.0.0.1", port, proc)

        def stop(proc):
            if proc.is_alive():
                proc.terminate()
            proc.join(timeout=10)

        scaler = Autoscaler(
            pool,
            gw.server.signals,
            spawn,
            stop,
            min_replicas=2,
            max_replicas=3,
            scale_up_queue_depth=64.0,
            scale_down_queue_depth=4.0,
            consecutive=2,
            cooldown_up_s=1.0,
            cooldown_down_s=1.5,
            drain_grace_s=0.1,
            interval_s=0.3,
        ).start()
        stats = asyncio.run(
            _run_gateway_async(
                seed, procs, ports, victim, params, gw, scaler, log
            )
        )
        result.update(stats)
    except Exception as e:  # noqa: BLE001 - every failure becomes a record
        result["ok"] = False
        result["error"] = f"{type(e).__name__}: {e}"
        try:
            result["bundle"] = write_incident_bundle(
                "chaos-gateway-violation",
                attrs={"seed": seed, "violation": str(e)[:500]},
            )
        except Exception as be:  # pragma: no cover - disk trouble
            result["bundle"] = f"<bundle write failed: {be}>"
    finally:
        if scaler is not None:
            scaler.stop(drain_owned=True)
        if gw is not None:
            gw.stop()
        if pool is not None:
            pool.close()
        for proc in procs + extra_procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs + extra_procs:
            proc.join(timeout=10)
        flightrec.clear()
    return result


# -- the shard lane (ISSUE 13) ----------------------------------------------


def _serve_mid_node(port: int, leaf_ports) -> None:
    """One MID-TIER aggregator of the tree: serves TCP, forwards
    reduce windows to its leaf pool (`make_aggregator_compute`).  A
    PFTPU_FAULT_PLAN inherited from the parent env was activated at
    package import — the shard fault kinds fire at this node's
    ``partition.reply`` seam, and kill_process models a mid-tier dying
    DURING tree aggregation."""
    import logging

    logging.disable(logging.ERROR)

    from pytensor_federated_tpu.routing import (
        NodePool,
        PooledArraysClient,
        make_aggregator_compute,
    )
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    pool = NodePool(
        [("127.0.0.1", p) for p in leaf_ports], transport="tcp"
    )
    child = PooledArraysClient(pool)
    serve_tcp_once(
        make_aggregator_compute(child, window=8),
        "127.0.0.1",
        port,
        concurrent=True,
    )


def _spawn_mid(port: int, leaf_ports, plan_json=None):
    saved = os.environ.get(fi.runtime.ENV_VAR)
    if plan_json is not None:
        os.environ[fi.runtime.ENV_VAR] = plan_json
    else:
        os.environ.pop(fi.runtime.ENV_VAR, None)
    try:
        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=_serve_mid_node, args=(port, list(leaf_ports)),
            daemon=True,
        )
        proc.start()
    finally:
        if saved is None:
            os.environ.pop(fi.runtime.ENV_VAR, None)
        else:
            os.environ[fi.runtime.ENV_VAR] = saved
    return proc


def _shard_mid_templates():
    """Node-side rules for the victim MID-TIER: the three shard kinds
    at its partition.reply seam, compute faults, and SIGKILL during
    tree aggregation."""
    return [
        ("drop_shard", dict(point="partition.reply", max_fires=1)),
        ("dup_shard", dict(point="partition.reply", max_fires=1)),
        ("corrupt_shard", dict(point="partition.reply", max_fires=1)),
        ("compute_error", dict(point="server.compute", max_fires=1)),
        ("kill_process", dict(point="server.compute", max_fires=1)),
        ("disconnect", dict(point="tcp.send", max_fires=1)),
    ]


async def _run_shard_async(seed, mids, mid_ports, leaf_ports, log):
    """Reduce-scatter tree under chaos.  Invariants:

    S1 correctness — every ``evaluate_reduced`` either returns the
       EXACT known sums (head and every flat element checked) or
       raises a loud, classified error — never a silently-wrong or
       partial gradient (the loud-reassembly contract);
    S2 no hang — every call settles within CALL_DEADLINE_S, a
       SIGKILLed mid-tier included;
    S3 reconverge — once faults stop and the dead mid-tier is
       respawned, breakers close and a clean reduce returns the exact
       sums through the full tree.
    """
    from pytensor_federated_tpu.routing import NodePool, PooledArraysClient

    pool = NodePool(
        [("127.0.0.1", p) for p in mid_ports],
        transport="tcp",
        breaker_kwargs=dict(
            failure_threshold=2, backoff_s=0.2, jitter_frac=0.1
        ),
        probe_timeout_s=2.0,
    )
    client = PooledArraysClient(pool)
    n_loud = 0

    n_requests = 12
    reqs = [
        (np.array([float(i), 5.0], np.float64),) for i in range(n_requests)
    ]
    want_head = sum(_expected(float(i)) for i in range(n_requests))
    want_flat = np.sum(
        [-2.0 * (np.array([float(i), 5.0]) - 3.0) for i in range(n_requests)],
        axis=0,
    )

    async def deadline(coro):
        return await asyncio.wait_for(coro, timeout=CALL_DEADLINE_S)

    def check(out, where):
        if out is None:
            raise Violation(f"{where}: silently unreplied reduce")
        head, flat = out
        if not np.isclose(float(np.asarray(head)), want_head, rtol=1e-9):
            raise Violation(
                f"{where}: head {float(np.asarray(head))} != "
                f"{want_head} (SILENTLY WRONG GRADIENT)"
            )
        if not np.allclose(np.asarray(flat), want_flat, rtol=1e-9):
            raise Violation(
                f"{where}: flat gradient mismatch (SILENTLY WRONG "
                "GRADIENT)"
            )

    try:
        # Phase A: reduce windows through the tree, chaos live.
        for w in range(10):
            try:
                out = await deadline(
                    client.evaluate_reduced_async(
                        reqs, window=8, slices=(w % 3) + 1, total=2
                    )
                )
            except asyncio.TimeoutError:
                raise Violation(f"reduce {w}: hang past {CALL_DEADLINE_S}s")
            except Exception as e:
                if not _is_loud(e):
                    raise Violation(
                        f"reduce {w}: UNCLASSIFIED error escaped "
                        f"({type(e).__name__}: {str(e)[:200]})"
                    )
                n_loud += 1
                log(f"  reduce {w}: loud ({type(e).__name__}: "
                    f"{str(e)[:80]})")
            else:
                check(out, f"reduce {w}")

        # Phase B: faults stop -> respawn dead/victim mid-tiers, then
        # the tree must serve a clean, exact reduce.
        fi.uninstall()
        for k, proc in enumerate(mids):
            if not proc.is_alive():
                log(f"  mid-tier {k} died (kill_process?): respawning")
                mids[k] = _spawn_mid(mid_ports[k], leaf_ports, None)
        await _wait_nodes_up_async("tcp", mid_ports)
        deadline_t = time.time() + 30.0
        while time.time() < deadline_t:
            await pool.probe_once_async()
            if all(r.breaker.state == "closed" for r in pool.replicas):
                break
            await asyncio.sleep(0.1)
        bad = [
            (r.address, r.breaker.state)
            for r in pool.replicas
            if r.breaker.state != "closed"
        ]
        if bad:
            raise Violation(
                f"breakers never reconverged after faults stopped: {bad}"
            )
        out = await deadline(
            client.evaluate_reduced_async(reqs, window=8, slices=2, total=2)
        )
        check(out, "clean reduce")
    finally:
        fi.uninstall()
        pool.close()
    return n_loud


def run_shard_seed(seed: int, verbose: bool) -> dict:
    """One shard-lane scenario (``--lane shard``): a 2x2 aggregation
    tree (4 leaf nodes, 2 mid-tiers, driver pool over the mid-tiers)
    serving reduce-scatter windows while one mid-tier runs a seeded
    plan of shard faults (dropped/duplicated/corrupt slices, compute
    errors, SIGKILL mid-aggregation) and the driver runs byte-lane
    faults; same result-dict shape as the transport lanes."""

    def log(msg):
        if verbose:
            print(msg, flush=True)

    rng = random.Random(seed)
    # Driver-side byte faults on the mid-tier links.
    driver_rules = []
    for kind, kw in rng.sample(
        [
            ("delay", dict(point="tcp.send", delay_s=0.02, max_fires=2)),
            ("disconnect", dict(point="tcp.send", max_fires=1)),
            ("corrupt_bytes", dict(point="tcp.recv", max_fires=1)),
            ("drop", dict(point="pool.probe", max_fires=2)),
        ],
        rng.randint(1, 2),
    ):
        driver_rules.append(fi.FaultRule(kind, **dict(kw)))
    driver_plan = fi.FaultPlan(
        driver_rules, seed=seed, plan_id=f"shard-{seed}-driver"
    )
    # Node-side shard faults on ONE victim mid-tier.
    node_rules = []
    for kind, kw in rng.sample(_shard_mid_templates(), rng.randint(1, 3)):
        kw = dict(kw)
        if rng.random() < 0.6:
            kw["nth"] = rng.randint(1, 6)
            kw.pop("max_fires", None)
        node_rules.append(fi.FaultRule(kind, **kw))
    node_plan_json = fi.FaultPlan(
        node_rules, seed=seed, plan_id=f"shard-{seed}-mid"
    ).to_json()

    log(
        f"seed {seed}: driver {[r.to_dict() for r in driver_plan.rules]}, "
        f"mid {[r.to_dict() for r in node_rules]}"
    )
    tspans.set_enabled(True)
    flightrec.set_enabled(True)
    if flightrec.capacity() < 16384:
        flightrec.set_capacity(16384)
    telemetry.clear_traces()
    flightrec.clear()
    reunion.clear()

    leaf_ports = _free_ports(4)
    mid_ports = _free_ports(2)
    victim = rng.randrange(2)
    leaves = [_spawn_node("tcp", p, None) for p in leaf_ports]
    result = {"seed": seed, "transport": "shard", "ok": True}
    mids = []
    try:
        _wait_nodes_up("tcp", leaf_ports)
        mids = [
            _spawn_mid(
                p,
                leaf_ports[2 * k : 2 * k + 2],
                node_plan_json if k == victim else None,
            )
            for k, p in enumerate(mid_ports)
        ]
        _wait_nodes_up("tcp", mid_ports)
        fi.install(driver_plan)
        n_loud = asyncio.run(
            _run_shard_async(seed, mids, mid_ports, leaf_ports, log)
        )
        result["loud_errors"] = n_loud
        result["faults_fired"] = driver_plan.total_fires
    except Violation as v:
        bundle = write_incident_bundle(
            f"chaos-shard-seed-{seed}",
            attrs={"seed": seed, "violation": str(v)[:500]},
        )
        result.update(ok=False, error=str(v), bundle=bundle)
    except Exception as e:  # harness bug: loud, with a bundle
        bundle = write_incident_bundle(
            f"chaos-shard-seed-{seed}-harness",
            attrs={"seed": seed, "error": f"{type(e).__name__}: {e}"},
        )
        result.update(
            ok=False,
            error=f"harness: {type(e).__name__}: {e}",
            bundle=bundle,
        )
    finally:
        fi.uninstall()
        for proc in list(mids) + leaves:
            if proc.is_alive():
                proc.terminate()
        for proc in list(mids) + leaves:
            proc.join(timeout=10)
    return result


# -- the streaming lane (ISSUE 15) ------------------------------------------


def _streaming_compiled(placement=None):
    """The radon-8 ppl model BOTH sides build — driver and node
    children import this same function, so the per-shard compute
    cannot drift between them (the make_node_compute contract)."""
    from pytensor_federated_tpu import ppl
    from pytensor_federated_tpu.ppl.radon import make_radon_example

    model, args, _ = make_radon_example(8, mean_obs=6, seed=7)
    return ppl.compile(model, args, placement=placement)


def _serve_ppl_node(port: int) -> None:
    """One streaming-lane replica: the ppl-compiled radon per-shard
    ``[logp, *grads]`` compute over TCP.  A PFTPU_FAULT_PLAN inherited
    from the parent env was activated at package import — the rules
    fire at this node's server.compute / tcp.* seams."""
    import logging

    logging.disable(logging.ERROR)

    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    compiled = _streaming_compiled()
    serve_tcp_once(
        compiled.node_compute(), "127.0.0.1", port, concurrent=True
    )


def _spawn_ppl_node(port, plan_json=None):
    saved = os.environ.get(fi.runtime.ENV_VAR)
    if plan_json is not None:
        os.environ[fi.runtime.ENV_VAR] = plan_json
    else:
        os.environ.pop(fi.runtime.ENV_VAR, None)
    try:
        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=_serve_ppl_node, args=(port,), daemon=True
        )
        proc.start()
    finally:
        if saved is None:
            os.environ.pop(fi.runtime.ENV_VAR, None)
        else:
            os.environ[fi.runtime.ENV_VAR] = saved
    return proc


def _streaming_node_templates():
    """Victim-node rules: a stall past the step deadline (must become
    a SHED minibatch), compute errors (a skipped batch), and byte
    faults on the reply path (classified transient skips)."""
    return [
        ("slow_compute", dict(point="server.compute", delay_s=8.0,
                              max_fires=2)),
        ("compute_error", dict(point="server.compute", max_fires=2)),
        ("disconnect", dict(point="tcp.send", max_fires=1)),
        ("delay", dict(point="tcp.send", delay_s=0.05, max_fires=3)),
    ]


def run_streaming_seed(seed: int, verbose: bool) -> dict:
    """One streaming-SVI scenario (``--lane streaming``): the gateway
    feeds a :class:`~pytensor_federated_tpu.ppl.StreamingSVI` driver
    from a 2-replica pool while one replica runs a seeded fault plan,
    one replica flaps (killed mid-stream, respawned), and a hog tenant
    floods the front door.  Invariants (ISSUE 15 acceptance):

    T1 no double-count — the optimizer's OWN step counter equals the
       accepted-batch count equals the ELBO-trace length: a shed
       minibatch provably never stepped the optimizer, and no batch
       stepped it twice;
    T2 exact accounting — offered == accepted + skipped, and the
       ``pftpu_svi_batches_total{outcome=accepted}`` counter moved by
       exactly the accepted count (the step-counter telemetry the
       acceptance criterion names);
    T3 goodput floor — despite the faults, the flap, and the hog,
       at least ``goodput_floor`` of offered batches are accepted;
    T4 ELBO envelope — over the accepted steps the ELBO improves
       (mean of the last third above the mean of the first third):
       sheds may slow convergence, never corrupt it;
    T5 no hang — every step settles within CALL_DEADLINE_S;
    T6 fairness — the hog tenant drew at least one loud quota denial
       while the svi tenant kept its goodput.
    """

    def log(msg):
        if verbose:
            print(msg, flush=True)

    import jax

    from pytensor_federated_tpu import fed, ppl
    from pytensor_federated_tpu.gateway import GatewayThread, TenantFairness
    from pytensor_federated_tpu.gateway.fairness import is_overload_error
    from pytensor_federated_tpu.ppl.svi import SVI_BATCHES
    from pytensor_federated_tpu.routing import NodePool
    from pytensor_federated_tpu.service.tcp import TcpArraysClient

    rng = random.Random(seed ^ 0x57E4)
    params = {
        "n_batches": 42,
        "batch": 4,
        "deadline_s": 6.0,
        "goodput_floor": 0.55,
        "envelope_min_accepted": 18,
        "flap_after_s": rng.uniform(1.0, 3.0),
        "flap_down_s": rng.uniform(0.5, 1.5),
        # Quota sized so the svi tenant (~25 req/s in 8-item spikes)
        # stays inside while the hog's CONCURRENT 25-item windows
        # (3 connections firing at once — admission is instant, so
        # the spike lands before the node computes anything) blow
        # straight through the burst.
        "quota_rate_per_s": 120.0,
        "quota_burst": 30.0,
        "hog_conns": 3,
        "hog_windows": 12,
        "hog_window_items": 25,
    }
    node_rules = []
    for kind, kw in rng.sample(
        _streaming_node_templates(), rng.randint(1, 3)
    ):
        kw = dict(kw)
        if rng.random() < 0.5:
            kw["nth"] = rng.randint(3, 9)
            kw.pop("max_fires", None)
        node_rules.append(fi.FaultRule(kind, **kw))
    node_plan_json = fi.FaultPlan(
        node_rules, seed=seed, plan_id=f"streaming-{seed}-node"
    ).to_json()
    log(
        f"streaming seed {seed}: {params}, victim rules "
        f"{[r.to_dict() for r in node_rules]}"
    )
    tspans.set_enabled(True)
    flightrec.set_enabled(True)
    if flightrec.capacity() < 16384:
        flightrec.set_capacity(16384)
    flightrec.clear()

    ports = _free_ports(2)
    victim = rng.randrange(2)
    flap_target = 1 - victim  # the healthy replica flaps
    procs = [
        _spawn_ppl_node(p, node_plan_json if k == victim else None)
        for k, p in enumerate(ports)
    ]
    result = {"seed": seed, "transport": "streaming", "ok": True}
    pool = None
    gw = None
    cli = None
    stop_evt = threading.Event()
    hog_denied = []
    threads = []
    try:
        _wait_nodes_up("tcp", ports)
        pool = NodePool(
            [("127.0.0.1", p) for p in ports],
            transport="tcp",
            probe_interval_s=0.3,
            probe_timeout_s=2.0,
            breaker_kwargs=dict(
                failure_threshold=2, backoff_s=0.2, jitter_frac=0.1
            ),
        )
        pool.start()
        gw = GatewayThread(
            pool,
            fairness=TenantFairness(
                quota_rate_per_s=params["quota_rate_per_s"],
                quota_burst=params["quota_burst"],
                max_backlog_per_tenant=4096,
            ),
            frame_items=16,
        )
        gw.start()
        cli = TcpArraysClient("127.0.0.1", gw.port, tenant="svi")
        compiled = _streaming_compiled(
            placement=fed.PoolPlacement(cli, window=8, tag="svi")
        )
        svi = ppl.StreamingSVI(
            compiled,
            key=jax.random.PRNGKey(seed),
            n_mc=2,
            learning_rate=5e-2,
            deadline_s=None,  # warmup: no budget while jits compile
        )
        batches = np.random.default_rng(seed)

        def next_batch():
            return batches.choice(
                8, size=params["batch"], replace=False
            )

        # Warm the driver trace and both node jit caches without a
        # deadline, then baseline the ledger: the invariants cover the
        # chaos phase only (warmup steps may already meet node-plan
        # faults — they are part of the run, just not of the floor).
        for _ in range(3):
            svi.step(next_batch())
        base = dict(
            offered=svi.offered,
            accepted=svi.accepted,
            opt=svi.opt_steps,
            elbo=len(svi.elbo_trace),
            skipped=sum(svi.skipped.values()),
            counter=SVI_BATCHES.labels(outcome="accepted").value,
        )

        hog_req = tuple(
            np.asarray(leaf)
            for leaf in jax.tree_util.tree_leaves(
                compiled.init_params()
            )
        ) + (np.int32(0),)

        def hog():
            hc = TcpArraysClient(
                "127.0.0.1", gw.port, tenant="hog", timeout_s=10.0
            )
            reqs = [hog_req] * params["hog_window_items"]
            try:
                for _ in range(params["hog_windows"]):
                    if stop_evt.is_set():
                        return
                    try:
                        hc.evaluate_many(
                            reqs, window=params["hog_window_items"]
                        )
                    except Exception as e:  # noqa: BLE001 - tallied
                        if is_overload_error(str(e)):
                            hog_denied.append(1)
                        else:
                            log(
                                f"  hog: {type(e).__name__}: "
                                f"{str(e)[:100]}"
                            )
            finally:
                try:
                    hc.close()
                except Exception:
                    pass

        def flapper():
            time.sleep(params["flap_after_s"])
            log(f"  flapping replica {flap_target}")
            proc = procs[flap_target]
            if proc.is_alive():
                proc.terminate()
            time.sleep(params["flap_down_s"])
            procs[flap_target] = _spawn_ppl_node(
                ports[flap_target], None
            )

        for target in [hog] * params["hog_conns"] + [flapper]:
            t = threading.Thread(target=target, daemon=True)
            t.start()
            threads.append(t)

        svi.deadline_s = params["deadline_s"]
        for i in range(params["n_batches"]):
            t0 = time.time()
            outcome = svi.step(next_batch())
            wall = time.time() - t0
            if wall > CALL_DEADLINE_S:
                raise Violation(
                    f"step {i}: {wall:.1f}s wall past "
                    f"{CALL_DEADLINE_S}s (hang)"
                )
            log(f"  batch {i}: {outcome} ({wall * 1e3:.0f} ms)")
        stop_evt.set()

        offered = svi.offered - base["offered"]
        accepted = svi.accepted - base["accepted"]
        opt_delta = svi.opt_steps - base["opt"]
        elbo_delta = len(svi.elbo_trace) - base["elbo"]
        skipped = sum(svi.skipped.values()) - base["skipped"]
        counter_delta = (
            SVI_BATCHES.labels(outcome="accepted").value
            - base["counter"]
        )
        # T1: the optimizer's own counter is the double-count proof.
        if not (opt_delta == accepted == elbo_delta):
            raise Violation(
                f"step accounting broke: opt_steps Δ{opt_delta}, "
                f"accepted Δ{accepted}, elbo Δ{elbo_delta} "
                "(double-counted or ghost gradient)"
            )
        # T2: every batch accounted exactly once, and the telemetry
        # step counter moved in lockstep.
        if offered != accepted + skipped:
            raise Violation(
                f"batch accounting broke: offered {offered} != "
                f"accepted {accepted} + skipped {skipped}"
            )
        if counter_delta != accepted:
            raise Violation(
                f"telemetry step counter Δ{counter_delta} != "
                f"accepted Δ{accepted}"
            )
        # T3: goodput floor.
        if accepted < params["goodput_floor"] * offered:
            raise Violation(
                f"goodput collapsed: {accepted}/{offered} accepted "
                f"(floor {params['goodput_floor']})"
            )
        # T4: ELBO monotone-ish envelope over the accepted steps.
        if accepted >= params["envelope_min_accepted"]:
            trace = svi.elbo_trace[base["elbo"] :]
            third = max(1, len(trace) // 3)
            first = float(np.mean(trace[:third]))
            last = float(np.mean(trace[-third:]))
            if not last > first:
                raise Violation(
                    f"ELBO envelope broke: first-third {first:.2f} "
                    f">= last-third {last:.2f}"
                )
        # T6: the hog drew loud denials while svi kept goodput.
        if not hog_denied:
            raise Violation(
                "hog never out-ran its quota — lane mis-tuned"
            )
        result.update(
            offered=offered,
            accepted=accepted,
            skipped_kinds=dict(svi.skipped),
            hog_denied=len(hog_denied),
            elbo_last=round(svi.elbo_trace[-1], 2)
            if svi.elbo_trace
            else None,
        )
    except Violation as v:
        bundle = write_incident_bundle(
            f"chaos-streaming-seed-{seed}",
            attrs={"seed": seed, "violation": str(v)[:500]},
        )
        result.update(ok=False, error=str(v), bundle=bundle)
    except Exception as e:  # harness bug: loud, with a bundle
        bundle = write_incident_bundle(
            f"chaos-streaming-seed-{seed}-harness",
            attrs={"seed": seed, "error": f"{type(e).__name__}: {e}"},
        )
        result.update(
            ok=False,
            error=f"harness: {type(e).__name__}: {e}",
            bundle=bundle,
        )
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=15)
        if cli is not None:
            try:
                cli.close()
            except Exception:
                pass
        if gw is not None:
            gw.stop()
        if pool is not None:
            pool.close()
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs:
            proc.join(timeout=10)
        flightrec.clear()
    return result


# -- the zero lane (ISSUE 16) -----------------------------------------------


def _serve_zero_node(port: int, store_root: str) -> None:
    """One sharded-optimizer OWNER replica: the radon ppl model's
    versioned update compute (node-owned optax state, shard-local
    adam) over TCP, checkpointing owned shards into the SHARED store
    root — a respawned or failed-over replica restoring a dead owner's
    checkpoint is what the lane verifies."""
    import logging

    logging.disable(logging.ERROR)

    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    from pytensor_federated_tpu.optim import ShardStore
    from pytensor_federated_tpu.ppl.svi import make_sharded_update_compute
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    compiled = _streaming_compiled()
    compute = make_sharded_update_compute(
        compiled, ShardStore(store_root), learning_rate=5e-2, n_mc=2
    )
    serve_tcp_once(compute, "127.0.0.1", port, concurrent=True)


def _spawn_zero_node(port, store_root, plan_json=None):
    saved = os.environ.get(fi.runtime.ENV_VAR)
    if plan_json is not None:
        os.environ[fi.runtime.ENV_VAR] = plan_json
    else:
        os.environ.pop(fi.runtime.ENV_VAR, None)
    try:
        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=_serve_zero_node, args=(port, store_root), daemon=True
        )
        proc.start()
    finally:
        if saved is None:
            os.environ.pop(fi.runtime.ENV_VAR, None)
        else:
            os.environ[fi.runtime.ENV_VAR] = saved
    return proc


def _zero_node_templates():
    """Victim-owner rules beyond the guaranteed SIGKILL: compute
    errors (a refused update the driver must shed loudly) and byte
    faults on the reply path (the maybe-applied ambiguity the version
    check disambiguates on retry)."""
    return [
        ("compute_error", dict(point="server.compute", max_fires=2)),
        ("disconnect", dict(point="tcp.send", max_fires=2)),
        ("delay", dict(point="tcp.send", delay_s=0.05, max_fires=3)),
    ]


def _zero_driver_templates():
    """Driver-side rules: twisted version stamps (the node must refuse
    and the driver must NOT count the batch), dropped param refreshes
    (recovery retries next step), and link delays."""
    return [
        ("stale_param_version",
         dict(point="optim.update.version", max_fires=2)),
        ("drop_param_refresh", dict(point="optim.refresh", max_fires=1)),
        ("delay", dict(point="tcp.send", delay_s=0.02, max_fires=2)),
    ]


def run_zero_seed(seed: int, verbose: bool) -> dict:
    """One sharded-optimizer scenario (``--lane zero``): a sharded
    :class:`StreamingSVI` driver over a 3-replica TCP pool carrying 2
    optimizer-state shards, every replica checkpointing owned shards
    into a SHARED store; one victim replica runs a seeded fault plan
    ALWAYS including a SIGKILL mid-update while the driver twists
    version stamps and drops refreshes.  Invariants (ISSUE 16):

    Z1 per-shard exactly-once — ``shard_opt_steps[k] ==
       shard_accepted[k]`` for every shard after every phase: a
       refused or failed shard update never moved the version, an
       applied one moved it exactly once (no double-step through
       SIGKILL + failover + retry), and versions only move UP;
    Z2 exact accounting — offered == accepted + sum(skipped), every
       shed batch classified (never a silent drop), and a version
       divergence would RAISE (WireError), never shed;
    Z3 no hang — every step settles within CALL_DEADLINE_S;
    Z4 restore — after faults stop and dead replicas respawn, steps
       accept again, and each shard's checkpoint in the shared store
       agrees BIT-EXACTLY (params and version) with the driver's
       parameter slice: replica death restored optimizer state, it
       did not reinvent it;
    Z5 goodput — chaos sheds stay bounded: >= 40% of chaos-phase
       batches accepted.
    """

    def log(msg):
        if verbose:
            print(msg, flush=True)

    import shutil
    import tempfile

    import jax

    from pytensor_federated_tpu.optim import ShardStore, ShardedOptimizer
    from pytensor_federated_tpu.ppl.svi import StreamingSVI
    from pytensor_federated_tpu.routing import NodePool
    from pytensor_federated_tpu.service.npwire import WireError

    rng = random.Random(seed ^ 0x2E80)
    params = {
        "n_batches": 30,
        "batch": 4,
        "deadline_s": 8.0,
        "goodput_floor": 0.4,
        "clean_attempts": 6,
        "clean_accepted": 3,
    }
    # The victim ALWAYS dies mid-update (the lane's namesake), plus
    # sampled extras.
    node_rules = [
        fi.FaultRule(
            "kill_process", point="server.compute",
            nth=rng.randint(2, 10),
        )
    ]
    for kind, kw in rng.sample(_zero_node_templates(), rng.randint(0, 2)):
        kw = dict(kw)
        if rng.random() < 0.5:
            kw["nth"] = rng.randint(2, 8)
            kw.pop("max_fires", None)
        node_rules.append(fi.FaultRule(kind, **kw))
    node_plan_json = fi.FaultPlan(
        node_rules, seed=seed, plan_id=f"zero-{seed}-node"
    ).to_json()
    driver_rules = [
        fi.FaultRule(kind, **dict(kw))
        for kind, kw in rng.sample(
            _zero_driver_templates(), rng.randint(1, 2)
        )
    ]
    driver_plan = fi.FaultPlan(
        driver_rules, seed=seed, plan_id=f"zero-{seed}-driver"
    )
    log(
        f"zero seed {seed}: driver "
        f"{[r.to_dict() for r in driver_rules]}, victim "
        f"{[r.to_dict() for r in node_rules]}"
    )
    tspans.set_enabled(True)
    flightrec.set_enabled(True)
    if flightrec.capacity() < 16384:
        flightrec.set_capacity(16384)
    flightrec.clear()

    store_root = tempfile.mkdtemp(prefix=f"pftpu-zero-{seed}-")
    ports = _free_ports(3)
    victim = rng.randrange(3)
    procs = [
        _spawn_zero_node(
            p, store_root, node_plan_json if k == victim else None
        )
        for k, p in enumerate(ports)
    ]
    result = {"seed": seed, "transport": "zero", "ok": True}
    pool = None
    try:
        _wait_nodes_up("tcp", ports)
        pool = NodePool(
            [("127.0.0.1", p) for p in ports],
            transport="tcp",
            probe_interval_s=0.3,
            probe_timeout_s=2.0,
            breaker_kwargs=dict(
                failure_threshold=2, backoff_s=0.2, jitter_frac=0.1
            ),
        )
        pool.start()
        compiled = _streaming_compiled()
        dim = int(
            sum(
                np.asarray(leaf).size
                for leaf in jax.tree_util.tree_leaves(
                    compiled.init_params()
                )
            )
        )
        opt = ShardedOptimizer(
            2 * dim, pool=pool, count=2, failover_retries=3
        )
        svi = StreamingSVI(
            compiled,
            key=jax.random.PRNGKey(seed),
            n_mc=2,
            learning_rate=5e-2,
            deadline_s=None,  # warmup: no budget while jits compile
            sharded=opt,
        )
        batches = np.random.default_rng(seed)

        def next_batch():
            return batches.choice(8, size=params["batch"], replace=False)

        def check_z1(where):
            if svi.shard_opt_steps != svi.shard_accepted:
                raise Violation(
                    f"{where}: per-shard accounting broke — "
                    f"opt_steps {svi.shard_opt_steps} != "
                    f"accepted {svi.shard_accepted} "
                    "(double-step or ghost version)"
                )

        def step_checked(i, where):
            prev = list(opt.versions)
            t0 = time.time()
            try:
                outcome = svi.step(next_batch())
            except WireError as e:
                raise Violation(
                    f"{where} {i}: version divergence escaped "
                    f"({str(e)[:200]})"
                )
            wall = time.time() - t0
            if wall > CALL_DEADLINE_S:
                raise Violation(
                    f"{where} {i}: {wall:.1f}s wall past "
                    f"{CALL_DEADLINE_S}s (hang)"
                )
            if any(v2 < v1 for v1, v2 in zip(prev, opt.versions)):
                raise Violation(
                    f"{where} {i}: shard version REWOUND "
                    f"{prev} -> {opt.versions}"
                )
            log(f"  {where} {i}: {outcome} ({wall * 1e3:.0f} ms) "
                f"versions={opt.versions}")
            return outcome

        # Warmup (node victim plan is live; that is part of the run),
        # then baseline the ledger for the goodput floor.
        for i in range(2):
            step_checked(i, "warmup")
        base_offered, base_accepted = svi.offered, svi.accepted

        fi.install(driver_plan)
        svi.deadline_s = params["deadline_s"]
        for i in range(params["n_batches"]):
            step_checked(i, "batch")
        fi.uninstall()

        check_z1("chaos phase")
        offered = svi.offered - base_offered
        accepted = svi.accepted - base_accepted
        skipped = sum(svi.skipped.values())
        if svi.offered != svi.accepted + skipped:
            raise Violation(
                f"batch accounting broke: offered {svi.offered} != "
                f"accepted {svi.accepted} + skipped {skipped}"
            )
        if accepted < params["goodput_floor"] * offered:
            raise Violation(
                f"goodput collapsed: {accepted}/{offered} accepted "
                f"(floor {params['goodput_floor']})"
            )

        # Phase B: respawn dead owners, wait for the pool to
        # reconverge, then the lane must accept again and the SHARED
        # store must agree bit-exactly with the driver.
        for k, proc in enumerate(procs):
            if not proc.is_alive():
                log(f"  owner {k} died (SIGKILL mid-update): respawning")
                procs[k] = _spawn_zero_node(ports[k], store_root, None)
        _wait_nodes_up("tcp", ports)
        deadline_t = time.time() + 30.0
        while time.time() < deadline_t:
            if all(
                r.breaker.state == "closed" for r in pool.replicas
            ):
                break
            time.sleep(0.1)
        clean_ok = 0
        for i in range(params["clean_attempts"]):
            outcome = step_checked(i, "clean")
            clean_ok = clean_ok + 1 if outcome == "accepted" else 0
            if clean_ok >= params["clean_accepted"]:
                break
        if clean_ok < params["clean_accepted"]:
            raise Violation(
                f"never reconverged: < {params['clean_accepted']} "
                f"consecutive accepted steps after faults stopped "
                f"(skipped={dict(svi.skipped)})"
            )
        check_z1("clean phase")

        flat = np.concatenate(
            [np.asarray(svi.mu).ravel(), np.asarray(svi.log_sd).ravel()]
        )
        store = ShardStore(store_root)
        for k, part in enumerate(opt.parts):
            state = store.load(part)
            if state is None:
                raise Violation(f"shard {k}: checkpoint vanished")
            if state.version != opt.versions[k]:
                raise Violation(
                    f"shard {k}: store version {state.version} != "
                    f"driver version {opt.versions[k]}"
                )
            driver_slice = flat[part.offset : part.offset + part.length]
            if not np.array_equal(state.params, driver_slice):
                raise Violation(
                    f"shard {k}: restored checkpoint params diverge "
                    "from the driver's slice (restore reinvented "
                    "state)"
                )
        result.update(
            offered=svi.offered,
            accepted=svi.accepted,
            skipped_kinds=dict(svi.skipped),
            shard_steps=list(svi.shard_opt_steps),
            faults_fired=driver_plan.total_fires,
        )
    except Violation as v:
        bundle = write_incident_bundle(
            f"chaos-zero-seed-{seed}",
            attrs={"seed": seed, "violation": str(v)[:500]},
        )
        result.update(ok=False, error=str(v), bundle=bundle)
    except Exception as e:  # harness bug: loud, with a bundle
        bundle = write_incident_bundle(
            f"chaos-zero-seed-{seed}-harness",
            attrs={"seed": seed, "error": f"{type(e).__name__}: {e}"},
        )
        result.update(
            ok=False,
            error=f"harness: {type(e).__name__}: {e}",
            bundle=bundle,
        )
    finally:
        fi.uninstall()
        if pool is not None:
            pool.close()
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs:
            proc.join(timeout=10)
        shutil.rmtree(store_root, ignore_errors=True)
        flightrec.clear()
    return result


# -- the linalg lane (ISSUE 19) ---------------------------------------------

#: The lane's fixed problem: a 64x64 SPD matrix in 16-tile blocks
#: (4x4 grid) over 2 block-store replicas — small enough that a seed
#: runs in seconds, large enough that every protocol leg (PUT,
#: CHOL_PANEL, TRSM_PANEL, SYRK_UPDATE) fires several times per
#: factorization, so a mid-step SIGKILL has real state to corrupt.
_LINALG_N = 64
_LINALG_B = 16


def _serve_linalg_node(port: int) -> None:
    """One block-store replica: the stateful ISSUE-19 compute (tiles
    pinned node-side, panel ops by block id) over TCP.  A
    PFTPU_FAULT_PLAN inherited from the parent env was activated at
    package import — kill_process at server.compute is the lane's
    namesake fault."""
    import logging

    logging.disable(logging.ERROR)

    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    from pytensor_federated_tpu.linalg import (
        BlockLayout,
        make_block_store_compute,
    )
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    lay = BlockLayout(_LINALG_N, _LINALG_N, _LINALG_B, _LINALG_B)
    serve_tcp_once(
        make_block_store_compute(lay), "127.0.0.1", port, concurrent=True
    )


def _spawn_linalg_node(port, plan_json=None):
    saved = os.environ.get(fi.runtime.ENV_VAR)
    if plan_json is not None:
        os.environ[fi.runtime.ENV_VAR] = plan_json
    else:
        os.environ.pop(fi.runtime.ENV_VAR, None)
    try:
        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=_serve_linalg_node, args=(port,), daemon=True
        )
        proc.start()
    finally:
        if saved is None:
            os.environ.pop(fi.runtime.ENV_VAR, None)
        else:
            os.environ[fi.runtime.ENV_VAR] = saved
    return proc


def _linalg_node_templates():
    """Victim rules beyond the guaranteed SIGKILL: byte faults on the
    reply path (the maybe-applied ambiguity the step stamps
    disambiguate) and link delays.  Every rule transient-classified —
    the driver must restore-and-retry, never assemble a partial
    factor."""
    return [
        ("disconnect", dict(point="tcp.send", max_fires=1)),
        ("delay", dict(point="tcp.send", delay_s=0.05, max_fires=3)),
    ]


def _linalg_driver_templates():
    """Driver-side rules: request-path disconnects (a HEALTHY replica's
    link dying must restore only that replica) and link delays."""
    return [
        ("disconnect", dict(point="tcp.send", max_fires=1)),
        ("delay", dict(point="tcp.send", delay_s=0.02, max_fires=2)),
        ("delay", dict(point="tcp.recv", delay_s=0.02, max_fires=2)),
    ]


def run_linalg_seed(seed: int, verbose: bool) -> dict:
    """One blocked-factorization scenario (``--lane linalg``): a
    :class:`~pytensor_federated_tpu.linalg.BlockedCholesky` driver over
    a 2-replica TCP block-store pool; the victim replica runs a seeded
    plan ALWAYS including a SIGKILL mid-factorization (a watcher thread
    respawns it cold — empty store) while the driver runs link faults.
    Invariants (ISSUE 19 acceptance):

    L1 never a silently wrong factor — ``factor()`` must complete and
       ``L @ L.T`` must reproduce ``A`` to f64 accuracy (and match
       ``np.linalg.cholesky`` — recovery recomputes trailing state
       driver-side through the same ``dot_kernel``, so the recovered
       factor is the no-fault factor, not merely a nearby one);
    L2 recovery locality — only replicas that actually LOST state
       (flight-recorded ``linalg.replica_lost``) re-ship tiles, every
       re-shipped tile belongs to that replica's block rows, and the
       guaranteed SIGKILL means at least one restore happened;
    L3 no hang — the factorization (including reconnect + respawn +
       re-ship) settles within ``CALL_DEADLINE_S``;
    L4 clean reconvergence + accounting — after faults stop, a fresh
       factorization over the SAME (respawned) replicas completes with
       ZERO restores, and every driver-fired fault left its ``fault.*``
       flight event.
    """

    def log(msg):
        if verbose:
            print(msg, flush=True)

    from pytensor_federated_tpu.linalg import BlockedCholesky, BlockLayout
    from pytensor_federated_tpu.service.tcp import TcpArraysClient

    rng = random.Random(seed ^ 0x11A6)
    lay = BlockLayout(_LINALG_N, _LINALG_N, _LINALG_B, _LINALG_B)
    mat_rng = np.random.default_rng(seed)
    a = mat_rng.normal(size=(_LINALG_N, _LINALG_N))
    a = a @ a.T / _LINALG_N + np.eye(_LINALG_N)
    ref = np.linalg.cholesky(a)

    # The victim ALWAYS dies mid-factorization; nth <= 6 lands inside
    # the first factor() no matter which replica is the victim (the
    # lighter-loaded replica serves 6 requests per clean run).
    node_rules = [
        fi.FaultRule(
            "kill_process", point="server.compute", nth=rng.randint(2, 6)
        )
    ]
    for kind, kw in rng.sample(_linalg_node_templates(), rng.randint(0, 2)):
        node_rules.append(fi.FaultRule(kind, **dict(kw)))
    node_plan_json = fi.FaultPlan(
        node_rules, seed=seed, plan_id=f"linalg-{seed}-node"
    ).to_json()
    driver_rules = [
        fi.FaultRule(kind, **dict(kw))
        for kind, kw in rng.sample(
            _linalg_driver_templates(), rng.randint(1, 2)
        )
    ]
    driver_plan = fi.FaultPlan(
        driver_rules, seed=seed, plan_id=f"linalg-{seed}-driver"
    )
    log(
        f"linalg seed {seed}: driver "
        f"{[r.to_dict() for r in driver_rules]}, victim "
        f"{[r.to_dict() for r in node_rules]}"
    )
    tspans.set_enabled(True)
    flightrec.set_enabled(True)
    if flightrec.capacity() < 16384:
        flightrec.set_capacity(16384)
    flightrec.clear()

    ports = _free_ports(2)
    victim = rng.randrange(2)
    procs = [
        _spawn_linalg_node(p, node_plan_json if k == victim else None)
        for k, p in enumerate(ports)
    ]
    result = {"seed": seed, "transport": "linalg", "ok": True}
    stop = threading.Event()
    respawns = [0, 0]

    def watcher():
        # Respawn dead replicas cold (no plan, EMPTY store): recovery
        # must re-ship state, it cannot find it waiting.
        while not stop.is_set():
            for k, proc in enumerate(procs):
                if not proc.is_alive():
                    respawns[k] += 1
                    log(f"  replica {k} died: respawning cold")
                    procs[k] = _spawn_linalg_node(ports[k], None)
            stop.wait(0.2)

    clients = []
    watch = threading.Thread(target=watcher, daemon=True)
    try:
        _wait_nodes_up("tcp", ports)
        watch.start()
        clients = [TcpArraysClient("127.0.0.1", p) for p in ports]
        chol = BlockedCholesky(
            lay,
            clients,
            reconnect=lambda p: TcpArraysClient("127.0.0.1", ports[p]),
            restore_attempts=6,
            reconnect_timeout_s=30.0,
        )
        fi.install(driver_plan)
        t0 = time.time()
        try:
            l_fact = chol.factor(a)
        except Exception as e:
            raise Violation(
                f"factorization failed to recover: "
                f"{type(e).__name__}: {str(e)[:300]}"
            )
        wall = time.time() - t0
        fi.uninstall()
        if wall > CALL_DEADLINE_S:
            raise Violation(
                f"factorization took {wall:.1f}s "
                f"(> {CALL_DEADLINE_S}s: hang)"
            )
        resid = float(np.max(np.abs(l_fact @ l_fact.T - a)))
        if resid > 1e-8 or not np.allclose(l_fact, ref, atol=1e-8):
            raise Violation(
                f"WRONG FACTOR survived recovery: max|LL^T - A| = "
                f"{resid:.3e} (restores={chol.restores})"
            )
        lost = {
            e["replica"]
            for e in flightrec.events()
            if e["kind"] == "linalg.replica_lost"
        }
        if chol.restores < 1:
            raise Violation(
                "the guaranteed SIGKILL never surfaced: zero restores "
                f"(lost={sorted(lost)}, respawns={respawns})"
            )
        bad_owner = [
            (p, c)
            for p, c in chol.reshipped
            if c[0] % len(clients) != p
        ]
        if bad_owner:
            raise Violation(
                f"re-shipped tiles outside the dead replica's rows: "
                f"{bad_owner[:8]}"
            )
        leaked = {p for p, _ in chol.reshipped} - lost
        if leaked:
            raise Violation(
                f"replicas {sorted(leaked)} re-shipped tiles without "
                f"ever losing state (lost={sorted(lost)}) — recovery "
                "is not local"
            )
        log(
            f"  chaos factor ok: wall {wall:.1f}s, restores "
            f"{chol.restores}, reshipped {len(chol.reshipped)}, "
            f"resid {resid:.1e}"
        )

        # L4a: accounting — every driver-side fired fault left its
        # flight event.
        fault_events = [
            e
            for e in flightrec.events()
            if e["kind"].startswith("fault.")
            and e["kind"][6:] in fi.FAULT_KINDS
        ]
        if len(fault_events) != driver_plan.total_fires:
            raise Violation(
                f"telemetry accounting: plan fired "
                f"{driver_plan.total_fires} faults but "
                f"{len(fault_events)} fault.* events were recorded"
            )

        # L4b: clean reconvergence — same replicas, fresh driver, a
        # DIFFERENT matrix, zero restores allowed.
        a2 = a + np.eye(_LINALG_N)
        clean = BlockedCholesky(
            lay,
            chol.clients,
            reconnect=lambda p: TcpArraysClient("127.0.0.1", ports[p]),
        )
        t0 = time.time()
        l2 = clean.factor(a2)
        wall2 = time.time() - t0
        if wall2 > CALL_DEADLINE_S:
            raise Violation(f"clean factor took {wall2:.1f}s (hang)")
        if clean.restores != 0:
            raise Violation(
                f"clean phase needed {clean.restores} restores after "
                "faults stopped — never reconverged"
            )
        if not np.allclose(l2, np.linalg.cholesky(a2), atol=1e-8):
            raise Violation("clean-phase factor diverged")
        result.update(
            restores=chol.restores,
            reshipped=len(chol.reshipped),
            respawns=sum(respawns),
            faults_fired=driver_plan.total_fires,
            wall_s=round(wall, 1),
        )
    except Violation as v:
        bundle = write_incident_bundle(
            f"chaos-linalg-seed-{seed}",
            attrs={"seed": seed, "violation": str(v)[:500]},
        )
        result.update(ok=False, error=str(v), bundle=bundle)
    except Exception as e:  # harness bug: loud, with a bundle
        bundle = write_incident_bundle(
            f"chaos-linalg-seed-{seed}-harness",
            attrs={"seed": seed, "error": f"{type(e).__name__}: {e}"},
        )
        result.update(
            ok=False,
            error=f"harness: {type(e).__name__}: {e}",
            bundle=bundle,
        )
    finally:
        fi.uninstall()
        stop.set()
        if watch.is_alive():
            watch.join(timeout=5)
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs:
            proc.join(timeout=10)
        flightrec.clear()
    return result


def run_seed(seed: int, transport: str, verbose: bool) -> dict:
    """One full chaos scenario; returns a result dict, raising nothing —
    violations land in the dict with an incident-bundle path."""

    def log(msg):
        if verbose:
            print(msg, flush=True)

    n_requests = 12
    driver_plan, node_plan_json, n_replicas = generate_plans(
        seed, transport, n_requests
    )
    log(
        f"seed {seed}: {n_replicas} replicas, driver rules "
        f"{[r.to_dict() for r in driver_plan.rules]}, node plan "
        f"{'yes' if node_plan_json else 'no'}"
    )
    tspans.set_enabled(True)
    flightrec.set_enabled(True)
    # The accounting invariant counts fault.* events across the whole
    # seed; the default 512-event ring would evict early faults under
    # a span-event flood, making the check lie.
    if flightrec.capacity() < 16384:
        flightrec.set_capacity(16384)
    telemetry.clear_traces()
    flightrec.clear()
    reunion.clear()

    ports = _free_ports(n_replicas)
    victim = random.Random(seed ^ 0x5EED).randrange(n_replicas)
    procs = [
        _spawn_node(
            transport, p, node_plan_json if k == victim else None
        )
        for k, p in enumerate(ports)
    ]
    result = {"seed": seed, "transport": transport, "ok": True}
    try:
        _wait_nodes_up(transport, ports)
        fi.install(driver_plan)
        n_loud = asyncio.run(
            _run_seed_async(
                seed, transport, procs, ports, driver_plan,
                victim, node_plan_json is not None, log,
            )
        )
        result["loud_errors"] = n_loud

        # Invariant 4: telemetry accounting — every driver-side fired
        # fault left its flight event.
        fault_events = [
            e
            for e in flightrec.events()
            if e["kind"].startswith("fault.")
            and e["kind"][6:] in fi.FAULT_KINDS
        ]
        fired = driver_plan.total_fires
        if len(fault_events) != fired:
            raise Violation(
                f"telemetry accounting: plan fired {fired} faults but "
                f"{len(fault_events)} fault.* events were recorded"
            )
        result["faults_fired"] = fired
    except Exception as e:  # noqa: BLE001 - every failure becomes a record
        result["ok"] = False
        result["error"] = f"{type(e).__name__}: {e}"
        try:
            result["bundle"] = write_incident_bundle(
                "chaos-violation",
                attrs={"seed": seed, "violation": str(e)[:500]},
            )
        except Exception as be:  # pragma: no cover - disk trouble
            result["bundle"] = f"<bundle write failed: {be}>"
    finally:
        fi.uninstall()
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs:
            proc.join(timeout=10)
        telemetry.clear_traces()
        flightrec.clear()
        reunion.clear()
    return result


def main(argv=None) -> int:
    import logging

    # Chaos makes the transports loud by design (drop warnings, failed
    # compute tracebacks); the per-seed verdict lines are the signal.
    logging.disable(logging.WARNING)
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=25,
                    help="sweep seeds base..base+N-1 (default 25)")
    ap.add_argument("--seed", type=int, default=None,
                    help="run exactly one seed (replay a failure)")
    ap.add_argument("--base-seed", type=int, default=0)
    ap.add_argument("--transport", "--lane", dest="transport",
                    choices=("grpc", "tcp", "shm", "ring", "overload",
                             "collector", "gateway", "shard",
                             "streaming", "zero", "linalg"),
                    default="grpc",
                    help="transport lane under chaos (--lane is an "
                    "alias; 'shm' runs the zero-copy arena lane; "
                    "'ring' runs the ISSUE-18 zero-syscall lane: "
                    "seqlock descriptor rings in the arena under torn "
                    "records, future-lap stamps, swallowed futex "
                    "wakes, dropped replies, and a SIGKILLed node — "
                    "every fault loud, parked waiters never hang; "
                    "'overload' runs the ISSUE-10 scenario: 2x-"
                    "oversubscribed clients, one stalling replica, "
                    "deadline/shed/budget invariants; 'collector' "
                    "runs the ISSUE-11 scenario: fleet scrapes racing "
                    "replica SIGKILLs — no hangs, loud staleness, "
                    "never-torn merges; 'gateway' runs the ISSUE-12 "
                    "scenario: 1k downstream clients through the "
                    "front door, one hog tenant, a flapping replica — "
                    "fairness floors, tenant-labeled denials, zero "
                    "hangs, autoscaler convergence; 'shard' runs the "
                    "ISSUE-13 scenario: reduce-scatter windows over a "
                    "2x2 aggregation tree, one mid-tier dropping/"
                    "duplicating/corrupting shard slices and dying "
                    "mid-aggregation — loud reassembly, zero hangs, "
                    "no silently-wrong gradients; 'streaming' runs "
                    "the ISSUE-15 scenario: the gateway feeding "
                    "streaming SVI with a faulted replica, a flapping "
                    "replica, and a hog tenant — optimizer steps == "
                    "accepted batches, shed minibatches provably "
                    "skipped never double-counted, ELBO envelope "
                    "holds, goodput floor; 'zero' runs the ISSUE-16 "
                    "scenario: sharded-optimizer SVI over a 3-owner "
                    "pool with a replica SIGKILLed mid-update, "
                    "twisted version stamps and dropped refreshes — "
                    "per-shard opt_steps == accepted, loud stale "
                    "refusals, bit-exact checkpoint restore, zero "
                    "hangs; 'linalg' runs the ISSUE-19 scenario: "
                    "blocked Cholesky over a 2-replica block-store "
                    "pool with a replica SIGKILLed mid-factorization "
                    "and respawned cold — only the dead replica's "
                    "tiles re-ship, the recovered factor reproduces "
                    "A exactly, zero hangs, clean reconvergence)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    seeds = (
        [args.seed]
        if args.seed is not None
        else list(range(args.base_seed, args.base_seed + args.seeds))
    )
    t0 = time.time()
    failures = []
    for seed in seeds:
        if args.transport == "overload":
            res = run_overload_seed(seed, args.verbose)
        elif args.transport == "collector":
            res = run_collector_seed(seed, args.verbose)
        elif args.transport == "gateway":
            res = run_gateway_seed(seed, args.verbose)
        elif args.transport == "shard":
            res = run_shard_seed(seed, args.verbose)
        elif args.transport == "streaming":
            res = run_streaming_seed(seed, args.verbose)
        elif args.transport == "zero":
            res = run_zero_seed(seed, args.verbose)
        elif args.transport == "linalg":
            res = run_linalg_seed(seed, args.verbose)
        else:
            res = run_seed(seed, args.transport, args.verbose)
        status = "ok" if res["ok"] else "FAIL"
        if not res["ok"]:
            extra = f"{res['error']} bundle={res.get('bundle')}"
        elif args.transport == "gateway":
            extra = (
                f"ok={res.get('ok_calls')} denied={res.get('denied')} "
                f"hog_denied={res.get('hog_denied')} "
                f"transient={res.get('transient')}"
            )
        elif args.transport == "overload":
            extra = (
                f"ok={res.get('ok_calls')} shed={res.get('deadline_shed')} "
                f"transient={res.get('transient')}"
            )
        elif args.transport == "collector":
            extra = (
                f"sweeps={res.get('sweeps')} "
                f"stale_sweeps={res.get('stale_sweeps')}"
            )
        elif args.transport == "streaming":
            extra = (
                f"accepted={res.get('accepted')}/{res.get('offered')} "
                f"skipped={res.get('skipped_kinds')} "
                f"hog_denied={res.get('hog_denied')} "
                f"elbo={res.get('elbo_last')}"
            )
        elif args.transport == "zero":
            extra = (
                f"accepted={res.get('accepted')}/{res.get('offered')} "
                f"skipped={res.get('skipped_kinds')} "
                f"shard_steps={res.get('shard_steps')}"
            )
        elif args.transport == "linalg":
            extra = (
                f"restores={res.get('restores')} "
                f"reshipped={res.get('reshipped')} "
                f"respawns={res.get('respawns')} "
                f"faults={res.get('faults_fired')} "
                f"wall={res.get('wall_s')}s"
            )
        else:
            extra = (
                f"faults={res.get('faults_fired')} "
                f"loud={res.get('loud_errors')}"
            )
        print(f"chaos seed {seed}: {status} ({extra})", flush=True)
        if not res["ok"]:
            failures.append(res)
    wall = time.time() - t0
    print(
        json.dumps(
            {
                "chaos": {
                    "seeds": len(seeds),
                    "failures": len(failures),
                    "transport": args.transport,
                    "wall_s": round(wall, 1),
                }
            }
        )
    )
    if failures:
        print(
            f"\n{len(failures)} seed(s) violated invariants; replay with "
            f"`python tools/chaos_run.py --seed {failures[0]['seed']}"
            + (" --transport tcp" if args.transport == "tcp" else "")
            + "`",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
