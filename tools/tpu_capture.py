"""One-command TPU benchmark capture with the wedge policy built in.

Usage: ``python tools/tpu_capture.py [--skip-suite]``

The axon-tunneled chip WEDGES if a process dies mid-TPU-call (see
CLAUDE.md): a half-open claim blocks every later PJRT init, and the
wedge can last many hours.  This script encodes the safe procedure so
a capture can never be fumbled:

1. probe liveness in a subprocess under a timeout (never dials the
   plugin in-process) — exit non-zero immediately if wedged;
2. refuse to run if the machine is busy (concurrent load halves CPU
   numbers and slows TPU host dispatch);
3. run ``bench.py`` then ``bench_suite.py`` with NO timeout — a
   timeout that fires mid-TPU-call is exactly how the chip wedged in
   round 1 — letting every call complete;
4. verify the artifacts really say ``"backend": "tpu"`` and report.

Compiled Mosaic (Pallas) stays opt-in: pass ``--try-mosaic`` to let
the preflight probe it (in its own subprocess) and, if it survives,
export ``PFTPU_PALLAS_COMPILED=1`` for the bench.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Single source of truth for this script's exit codes (tools/tpu_poll.py
# imports this to label its attempt log; keep `return` sites in sync).
EXIT_MEANINGS = {
    0: "OK — artifacts captured with backend: tpu",
    1: "DEAD (probe timed out)",
    2: "LIVE but machine busy — not capturing",
    3: "bench.py printed no JSON line",
    4: "bench ran on non-tpu backend (re-wedge?)",
    5: "bench_suite.py failed",
    6: "suite backends not all-tpu (re-wedge mid-capture?)",
}


def machine_busy(threshold: float = 1.0) -> bool:
    load1 = os.getloadavg()[0]
    return load1 > threshold


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--skip-suite", action="store_true")
    parser.add_argument("--try-mosaic", action="store_true")
    parser.add_argument(
        "--force-busy",
        action="store_true",
        help="run even if load average says the machine is busy",
    )
    parser.add_argument(
        "--probe-timeout-s",
        type=float,
        default=180.0,
        help="liveness-probe timeout (size generously for a slow tunnel)",
    )
    args = parser.parse_args()

    sys.path.insert(0, REPO)
    from pytensor_federated_tpu.utils import probe_backend

    live, mosaic_ok = probe_backend(
        try_mosaic=args.try_mosaic, timeout_s=args.probe_timeout_s
    )
    if not live:
        print("TPU NOT live (probe timed out) — not capturing.", file=sys.stderr)
        return 1
    print(f"TPU live (mosaic_ok={mosaic_ok})", file=sys.stderr)

    if machine_busy() and not args.force_busy:
        print(
            "machine busy (load > 1) — refusing to capture skewed numbers; "
            "re-run when idle or pass --force-busy",
            file=sys.stderr,
        )
        return 2

    env = dict(os.environ)
    if args.try_mosaic and mosaic_ok:
        env["PFTPU_PALLAS_COMPILED"] = "1"

    # NO timeout on the bench runs: killing a process mid-TPU-call is
    # how the chip wedges for hours.  Worst case is bounded by the
    # bench's own sizing (a few minutes).
    print("== bench.py ==", file=sys.stderr)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
    )
    sys.stderr.write(out.stderr)
    print(out.stdout.strip())
    line = None
    for ln in out.stdout.splitlines():
        try:
            line = json.loads(ln)
            break
        except json.JSONDecodeError:
            continue
    if not line:
        print("bench.py printed no JSON line!", file=sys.stderr)
        return 3
    if line.get("backend") != "tpu":
        print(
            f"bench ran on {line.get('backend')!r}, not tpu — probe raced a "
            "re-wedge?",
            file=sys.stderr,
        )
        return 4

    if not args.skip_suite:
        print("== bench_suite.py ==", file=sys.stderr)
        suite_path = os.path.join(REPO, "BENCH_SUITE.json")
        try:
            mtime_before = os.path.getmtime(suite_path)
        except OSError:
            mtime_before = None
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench_suite.py")],
            cwd=REPO,
            env=env,
            capture_output=True,
            text=True,
        )
        sys.stderr.write(out.stderr)
        print(out.stdout.strip())
        if out.returncode != 0:
            # The suite guards each config and persists incrementally,
            # so the artifact holds every config that DID succeed in
            # THIS run — unless nothing recorded at all, in which case
            # the file on disk is a previous run's (mtime unchanged)
            # and must be reported as stale, not as this run's output.
            try:
                refreshed = os.path.getmtime(suite_path) != mtime_before
            except OSError:
                refreshed = False
            if refreshed:
                with open(suite_path) as f:
                    kept = [r.get("config") for r in json.load(f)]
                print(
                    f"bench_suite.py failed (exit {out.returncode}); "
                    f"artifact holds this run's successful configs: "
                    f"{kept}",
                    file=sys.stderr,
                )
            else:
                print(
                    f"bench_suite.py failed (exit {out.returncode}) "
                    "before recording anything — BENCH_SUITE.json on "
                    "disk is a PREVIOUS run's artifact",
                    file=sys.stderr,
                )
            return 5
        with open(os.path.join(REPO, "BENCH_SUITE.json")) as f:
            suite = json.load(f)
        backends = {r.get("backend") for r in suite}
        if backends != {"tpu"}:
            print(
                f"suite ran on {backends}, not all-tpu (re-wedge "
                "mid-capture?) — rejecting",
                file=sys.stderr,
            )
            return 6
        below = [
            r["config"]
            for r in suite
            if r.get("vs_baseline") is not None and r["vs_baseline"] < 1.0
        ]
        if below:
            print(f"configs below baseline: {below}", file=sys.stderr)

    print("capture complete", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
