"""Measure the bridge layer's pytensor-gated LoC surface.

These are lines of CODE (not blanks/comments/docstrings) in modules
that cannot import in this environment because pytensor/pymc are
uninstallable.  Since round 5 they all EXECUTE under the in-repo API
shim (tests/pytensor_shim.py + pymc_shim.py inject a minimal fake
pytensor/pymc and import the real modules) — the "shim-executed by"
column names the suite.  Shim execution proves our-side logic, not
real-pytensor compatibility; the distinction is documented in the shim
docstrings and docs/migrating.md.  Prints one line per file plus
totals; publish the numbers in docs/migrating.md when they change.
"""

import io
import sys
import tokenize
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# (path, shim-executed-by) — empty string = not executed anywhere.
PYTENSOR_GATED = [
    (
        "pytensor_federated_tpu/bridge/pytensor_ops.py",
        "tests/test_bridge_shim.py",
    ),
    (
        "pytensor_federated_tpu/bridge/fusion.py",
        "tests/test_bridge_shim.py",
    ),
    (
        "pytensor_federated_tpu/demos/demo_pymc.py",
        "tests/test_demo_pymc_shim.py",
    ),
]
EXECUTED_CORES = [
    "pytensor_federated_tpu/bridge/core.py",
    "pytensor_federated_tpu/bridge/grouping.py",
    "pytensor_federated_tpu/fanout_exec.py",
]


def code_lines(path: Path) -> int:
    """Count lines holding at least one real token (no comments,
    docstrings/bare string statements, or blank lines).

    A STRING token is a docstring (or bare string statement) exactly
    when it starts a LOGICAL line — i.e. the last significant token
    before it was NEWLINE/INDENT/DEDENT or the file start.  (A prefix-
    whitespace check is NOT enough: wrapped string arguments inside a
    call also start physical lines — review finding.)
    """
    src = path.read_text()
    lines = set()
    structural = (
        tokenize.COMMENT,
        tokenize.NL,
        tokenize.NEWLINE,
        tokenize.INDENT,
        tokenize.DEDENT,
        tokenize.ENCODING,
        tokenize.ENDMARKER,
    )
    at_logical_start = True
    for tok in tokenize.generate_tokens(io.StringIO(src).readline):
        if tok.type in structural:
            if tok.type in (
                tokenize.NEWLINE,
                tokenize.INDENT,
                tokenize.DEDENT,
            ):
                at_logical_start = True
            continue
        is_docstring = tok.type == tokenize.STRING and at_logical_start
        at_logical_start = False
        if is_docstring:
            continue
        for ln in range(tok.start[0], tok.end[0] + 1):
            lines.add(ln)
    return len(lines)


def main():
    total_un = 0
    total_shim = 0
    print("# pytensor/pymc-gated code lines (real packages uninstallable)")
    for rel, shim_suite in PYTENSOR_GATED:
        n = code_lines(REPO / rel)
        if shim_suite:
            total_shim += n
            print(f"{rel}: {n}  [shim-executed by {shim_suite}]")
        else:
            total_un += n
            print(f"{rel}: {n}  [UNEXECUTED]")
    print(f"TOTAL shim-executed: {total_shim}")
    print(f"TOTAL unexecuted: {total_un}")
    print("# executed pure cores they delegate to")
    total_core = 0
    for rel in EXECUTED_CORES:
        n = code_lines(REPO / rel)
        total_core += n
        print(f"{rel}: {n}")
    print(f"TOTAL executed cores: {total_core}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
