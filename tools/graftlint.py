"""Run graftlint from a checkout: ``python tools/graftlint.py [...]``.

Thin wrapper over ``python -m pytensor_federated_tpu.analysis`` that
(1) puts the repo root on ``sys.path`` so it works without an
installed package, and (2) restricts jax to the CPU backend via the
environment BEFORE the package import, so a lint run can never dial a
wedged tunneled-TPU plugin (CLAUDE.md environment pitfalls).  All
arguments pass through (``--json``, ``--rule``, ``--list-rules``,
paths).
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    sys.path.insert(0, str(REPO))
    from pytensor_federated_tpu.analysis.__main__ import main as cli

    return cli(sys.argv[1:])


if __name__ == "__main__":
    raise SystemExit(main())
