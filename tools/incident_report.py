#!/usr/bin/env python
"""Render a watchdog/crash incident bundle as a readable postmortem.

An incident bundle (``telemetry.write_incident_bundle`` — written by
the hang watchdog, the crash excepthook, or tools/tpu_poll.py on a
dead liveness probe) is one self-contained JSON file: all-thread
tracebacks, the flight-recorder tail, the metrics snapshot, and the
driver↔node trace reunion.  This tool turns it into the two formats a
postmortem actually gets read in:

- **markdown** (default): sections for the hang site (thread dump),
  the last N flight-recorder events as a table, the clock-aligned
  FLEET timeline (when a FleetCollector was live at bundle time:
  every replica's flight record interleaved onto the driver's clock,
  plus per-replica staleness/offset rows), the merged end-to-end
  call trees (driver encode → call → node decode/queue/compute/encode,
  indented per span), and a metrics digest.
- **JSONL** (``--jsonl``): one line per flight-recorder event plus one
  ``incident`` header line — greppable, and concatenates across
  incidents into a timeline.

Pure stdlib, never imports jax (safe on a machine whose TPU plugin is
the thing being debugged).

Usage:
    python tools/incident_report.py <bundle.json>             # markdown
    python tools/incident_report.py <bundle.json> --jsonl
    python tools/incident_report.py <bundle.json> -o out.md
"""

from __future__ import annotations

import argparse
import datetime
import json
import sys
from typing import List


def _ts(epoch: float) -> str:
    try:
        return datetime.datetime.fromtimestamp(
            epoch, datetime.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%S.%fZ")
    except (OverflowError, OSError, ValueError, TypeError):
        return str(epoch)


def _span_tree_lines(tree: dict, indent: int = 0) -> List[str]:
    pad = "  " * indent
    dur = tree.get("duration_s")
    dur_s = f" — {dur * 1e3:.3f} ms" if isinstance(dur, (int, float)) else ""
    err = tree.get("error")
    err_s = f"  **error: {err}**" if err else ""
    attrs = tree.get("attrs") or {}
    attr_s = (
        " (" + ", ".join(f"{k}={v}" for k, v in attrs.items()) + ")"
        if attrs
        else ""
    )
    lines = [f"{pad}- `{tree.get('name', '?')}`{attr_s}{dur_s}{err_s}"]
    for child in tree.get("children", ()):
        lines.extend(_span_tree_lines(child, indent + 1))
    return lines


def render_markdown(bundle: dict) -> str:
    out: List[str] = []
    out.append(f"# Incident: {bundle.get('reason', '?')}")
    out.append("")
    out.append(
        f"- **when:** {_ts(bundle.get('ts', 0))}  "
        f"**pid:** {bundle.get('pid', '?')}"
    )
    argv = bundle.get("argv")
    if argv:
        out.append(f"- **argv:** `{' '.join(map(str, argv))}`")
    attrs = bundle.get("attrs") or {}
    if attrs:
        out.append(
            "- **attrs:** "
            + ", ".join(f"`{k}={v}`" for k, v in attrs.items())
        )
    out.append("")

    threads = bundle.get("threads")
    out.append("## All-thread traceback (at incident time)")
    out.append("")
    if isinstance(threads, list):
        for th in threads:
            out.append(
                f"### thread `{th.get('name', '?')}` "
                f"(id {th.get('thread_id', '?')})"
            )
            out.append("")
            out.append("```")
            out.extend(th.get("stack", ()))
            out.append("```")
            out.append("")
    else:
        out.append(f"_unavailable: {threads}_")
        out.append("")

    events = bundle.get("flightrec")
    out.append("## Flight recorder (oldest first)")
    out.append("")
    if isinstance(events, list) and events:
        out.append("| seq | time | kind | trace | detail |")
        out.append("|---|---|---|---|---|")
        for ev in events:
            detail = {
                k: v
                for k, v in ev.items()
                if k not in ("seq", "ts", "kind", "trace_id")
            }
            out.append(
                f"| {ev.get('seq', '')} | {_ts(ev.get('ts', 0))} "
                f"| `{ev.get('kind', '?')}` "
                f"| {ev.get('trace_id', '')[:8]} "
                f"| {json.dumps(detail, default=str)} |"
            )
    else:
        out.append(f"_no events ({events!r})_")
    out.append("")

    plan = bundle.get("fault_plan")
    if isinstance(plan, dict):
        out.append("## Fault plan (chaos active at incident time)")
        out.append("")
        out.append(
            f"- **plan:** `{plan.get('plan_id', '?')}`  "
            f"**seed:** {plan.get('seed', '?')}  "
            f"**total fires:** {plan.get('total_fires', '?')}"
        )
        out.append("")
        rules = plan.get("rules")
        if isinstance(rules, list) and rules:
            out.append(
                "| # | kind | point | when | matches | fires | remaining |"
            )
            out.append("|---|---|---|---|---|---|---|")
            for r in rules:
                when = ", ".join(
                    f"{k}={r[k]}"
                    for k in ("nth", "every", "prob", "peer")
                    if r.get(k) is not None
                ) or "always"
                out.append(
                    f"| {r.get('index', '')} | `{r.get('kind', '?')}` "
                    f"| `{r.get('point', '*')}` | {when} "
                    f"| {r.get('matches', '')} | {r.get('fires', '')} "
                    f"| {r.get('remaining', '∞')} |"
                )
        out.append("")

    fleet_sections = bundle.get("fleet")
    if isinstance(fleet_sections, dict):
        # Pre-normalization bundles carried a lone collector's dict.
        fleet_sections = [fleet_sections]
    for fleet in fleet_sections if isinstance(fleet_sections, list) else ():
        out.append("## Fleet (clock-aligned cross-process timeline)")
        out.append("")
        stale = fleet.get("stale") or []
        unscraped = fleet.get("unscraped") or []
        out.append(
            f"- **sweep:** {_ts(fleet.get('ts', 0))}  "
            f"**complete:** {fleet.get('complete', '?')}"
            + (f"  **stale:** {', '.join(map(str, stale))}" if stale else "")
            + (
                f"  **unscraped:** {', '.join(map(str, unscraped))}"
                if unscraped
                else ""
            )
        )
        replicas = fleet.get("replicas")
        if isinstance(replicas, dict) and replicas:
            out.append("")
            out.append("| replica | up | rtt_ms | clock_offset_ms | error |")
            out.append("|---|---|---|---|---|")
            for addr in sorted(replicas):
                rep = replicas[addr] or {}
                rtt = rep.get("rtt_s")
                off = rep.get("clock_offset_s")
                out.append(
                    f"| `{addr}` | {'yes' if rep.get('ok') else 'NO'} "
                    f"| {'' if rtt is None else f'{1e3 * rtt:.2f}'} "
                    f"| {'' if off is None else f'{1e3 * off:+.2f}'} "
                    f"| {rep.get('error') or ''} |"
                )
        timeline = fleet.get("timeline")
        out.append("")
        if isinstance(timeline, list) and timeline:
            out.append(
                "| fleet time (driver clock) | replica | kind | detail |"
            )
            out.append("|---|---|---|---|")
            for ev in timeline:
                detail = {
                    k: v
                    for k, v in ev.items()
                    if k
                    not in (
                        "seq", "ts", "ts_fleet", "kind", "trace_id",
                        "replica",
                    )
                }
                out.append(
                    f"| {_ts(ev.get('ts_fleet', 0))} "
                    f"| `{ev.get('replica', '?')}` "
                    f"| `{ev.get('kind', '?')}` "
                    f"| {json.dumps(detail, default=str)} |"
                )
        else:
            out.append(f"_no timeline events ({timeline!r})_")
        out.append("")

    reunion = bundle.get("trace_reunion")
    out.append("## Trace reunion (driver + node span trees per call)")
    out.append("")
    if isinstance(reunion, list) and reunion:
        for tr in reunion:
            out.append(f"### trace `{tr.get('trace_id', '?')}`")
            out.append("")
            for side in ("driver", "remote"):
                trees = tr.get(side) or []
                out.append(f"**{side}** ({len(trees)} tree(s))")
                out.append("")
                for tree in trees:
                    out.extend(_span_tree_lines(tree))
                out.append("")
    else:
        out.append(f"_no correlated traces ({reunion!r})_")
        out.append("")

    telem = bundle.get("telemetry")
    out.append("## Metrics digest")
    out.append("")
    metrics = telem.get("metrics") if isinstance(telem, dict) else None
    if isinstance(metrics, dict) and metrics:
        out.append("| metric | labels | value |")
        out.append("|---|---|---|")
        for name in sorted(metrics):
            fam = metrics[name]
            for child in fam.get("children", ()):
                labels = child.get("labels") or {}
                label_s = ",".join(f"{k}={v}" for k, v in labels.items())
                if "count" in child:
                    val = (
                        f"count={child['count']} "
                        f"sum={child.get('sum', 0):.6g}"
                    )
                else:
                    val = f"{child.get('value', '')}"
                out.append(f"| `{name}` | {label_s} | {val} |")
    else:
        out.append(f"_unavailable ({metrics!r})_")
    out.append("")
    return "\n".join(out)


def render_jsonl(bundle: dict) -> str:
    lines = [
        json.dumps(
            {
                "record": "incident",
                "reason": bundle.get("reason"),
                "ts": bundle.get("ts"),
                "pid": bundle.get("pid"),
                "attrs": bundle.get("attrs"),
                "n_threads": len(bundle.get("threads") or ())
                if isinstance(bundle.get("threads"), list)
                else None,
                "n_traces": len(bundle.get("trace_reunion") or ())
                if isinstance(bundle.get("trace_reunion"), list)
                else None,
                "fault_plan": bundle.get("fault_plan"),
            },
            default=str,
        )
    ]
    events = bundle.get("flightrec")
    if isinstance(events, list):
        for ev in events:
            lines.append(json.dumps({"record": "event", **ev}, default=str))
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="path to an incident-*.json bundle")
    ap.add_argument(
        "--jsonl", action="store_true",
        help="emit JSONL (one line per flight-recorder event) instead "
        "of markdown",
    )
    ap.add_argument("-o", "--out", default=None, help="write here "
                    "instead of stdout")
    args = ap.parse_args(argv)

    try:
        with open(args.bundle, "r", encoding="utf-8") as fh:
            bundle = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"incident_report: cannot read {args.bundle}: {e}",
              file=sys.stderr)
        return 1
    if not isinstance(bundle, dict) or "reason" not in bundle:
        print(
            f"incident_report: {args.bundle} is not an incident bundle "
            "(no 'reason' key)",
            file=sys.stderr,
        )
        return 1

    text = render_jsonl(bundle) if args.jsonl else render_markdown(bundle)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"incident_report: wrote {args.out}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
