"""One-shot TPU numerics/perf diagnostic (round 3).

Evidence-gathering for three TPU-only anomalies in the first live
capture (tools/capture_attempts.log 2026-07-31T03:56:42Z, exit=5):

1. BENCH_SUITE config 6 (parallel-in-time Kalman) recorded an
   impossible 6.8e11 evals/s — hypothesis: default-precision f32
   matmuls on TPU degrade the scan compositions until the chain state
   degenerates (NaN or zero gradient), letting XLA hoist the eval out
   of the timing loop.
2. The suite then died (exit 1) — hypothesis: config 7's bf16-vs-f32
   equality gate fails because the "f32" reference itself ran at
   reduced matmul precision.
3. Config 4 (Lotka-Volterra ODE) fell from 62k evals/s (CPU) to 181
   (TPU) — sequential integrator latency; measure the per-eval wall to
   size the fix.

Run on a LIVE chip only, to completion (killing a process mid-TPU-call
wedges the relay, CLAUDE.md): ``python tools/diag_tpu.py > out 2>&1``.
"""

import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"backend={jax.default_backend()} kind={dev.device_kind}",
          flush=True)

    # --- 1. what does a default-precision f32 matmul actually do? ----
    rng = np.random.default_rng(0)
    A = rng.normal(size=(2048, 512)).astype(np.float32)
    w = rng.normal(size=(512,)).astype(np.float32)
    ref = A.astype(np.float64) @ w.astype(np.float64)

    for prec in ("default", "highest"):
        with jax.default_matmul_precision(prec):
            out = jax.jit(lambda a, b: a @ b)(jnp.asarray(A), jnp.asarray(w))
        err = np.max(
            np.abs(np.asarray(out, np.float64) - ref) / np.abs(ref)
        )
        print(f"f32 matmul precision={prec}: max relerr {err:.3e}",
              flush=True)

    # 1b. which mechanism recovers true-f32 accuracy?  Tests the
    # SHIPPED mechanisms (pytensor_federated_tpu.precision): the
    # per-site HIGHEST request and the 6-pass bf16x3 split behind
    # pdot/f32_policy.  ACCEPTANCE (round-3 verdict item 4): at least
    # one mechanism's norm-relative error <= 1e-5 on this 512-dot.
    # (Norm-relative, not elementwise max: individual outputs can
    # nearly cancel — plain f32 CPU maxes at 6e-4 elementwise on an
    # output with |ref| ~ 1.6e-3; the L2 ratio separates honest f32
    # ~1e-7 from bf16-degraded ~1e-3 unambiguously.)
    import sys

    sys.path.insert(0, "/root/repo")
    from jax import lax

    from pytensor_federated_tpu.precision import pdot, split_dot

    def dot_pref(a, b):
        return lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )

    refnorm = np.linalg.norm(ref)
    for name, fn in (
        ("pdot(highest)", lambda a, b: pdot(a, b, "highest")),
        ("dot_general(HIGHEST, pref=f32)", dot_pref),
        ("split_dot (6-pass bf16x3)", split_dot),
    ):
        out = jax.jit(fn)(jnp.asarray(A), jnp.asarray(w))
        d = np.asarray(out, np.float64) - ref
        err = np.max(np.abs(d) / np.abs(ref))
        nerr = np.linalg.norm(d) / refnorm
        verdict = "PASS" if nerr <= 1e-5 else "FAIL"
        print(
            f"f32 matvec via {name}: max relerr {err:.3e} "
            f"norm-rel {nerr:.3e} [{verdict} @1e-5]",
            flush=True,
        )

    # --- 2. parallel Kalman: finiteness + honest single-eval wall ----
    from jax.flatten_util import ravel_pytree

    from pytensor_federated_tpu.models.statespace import (
        generate_lgssm_data,
        kalman_logp_parallel,
    )

    y_ss, p_ss = generate_lgssm_data(T=4096)
    flat0, unravel = ravel_pytree(p_ss)

    # CPU float64-ish reference (CPU f32 is honest) for the acceptance
    # line: strict on chip must match CPU within 1e-4 relative.
    cpu0 = jax.devices("cpu")[0]
    with jax.default_device(cpu0):
        v_ref = float(
            jax.jit(lambda x: kalman_logp_parallel(unravel(x), y_ss))(
                jax.device_put(flat0, cpu0)
            )
        )

    # Every row passes its policy EXPLICITLY: precision=None would
    # re-resolve PFTPU_F32_POLICY at trace time, and a set env var
    # would silently contaminate the baseline rows this section exists
    # to measure.
    for prec in ("default", "highest", "strict"):
        fn = jax.jit(
            lambda x, _p=prec: jax.value_and_grad(
                lambda v: kalman_logp_parallel(
                    unravel(v), y_ss, precision=_p
                )
            )(x)
        )
        v, g = fn(flat0)
        jax.block_until_ready(g)
        t0 = time.perf_counter()
        for _ in range(5):
            v, g = fn(flat0)
        jax.block_until_ready(g)
        wall = (time.perf_counter() - t0) / 5
        g = np.asarray(g)
        rel = abs(float(v) - v_ref) / max(abs(v_ref), 1e-30)
        verdict = "PASS" if rel <= 1e-4 else "FAIL"
        print(
            f"kalman_parallel precision={prec}: v={float(v):.6g} "
            f"relerr_vs_cpu={rel:.3e} [{verdict} @1e-4] "
            f"grad_finite={np.isfinite(g).all()} "
            f"grad_absmax={np.abs(g).max():.3g} wall={wall * 1e3:.2f}ms",
            flush=True,
        )

    # --- 3. LV ODE per-eval wall -------------------------------------
    from pytensor_federated_tpu.models.ode import make_lv_model

    lv, _ = make_lv_model(8)
    p0 = lv.init_params()
    flat_lv, unr_lv = ravel_pytree(p0)
    fn_lv = jax.jit(
        lambda x: jax.value_and_grad(lambda v: lv.logp(unr_lv(v)))(x)
    )
    v, g = fn_lv(flat_lv)
    jax.block_until_ready(g)
    t0 = time.perf_counter()
    for _ in range(10):
        v, g = fn_lv(flat_lv)
    jax.block_until_ready(g)
    wall = (time.perf_counter() - t0) / 10
    print(
        f"lv_ode: v={float(v):.6g} grad_finite="
        f"{np.isfinite(np.asarray(g)).all()} wall={wall * 1e3:.2f}ms",
        flush=True,
    )

    # --- 4. config 7 gate: f32-vs-bf16 on the wide logistic ----------
    from pytensor_federated_tpu.models.logistic import (
        FederatedLogisticRegression,
        generate_logistic_data,
    )

    dataw, _ = generate_logistic_data(
        n_shards=8, n_obs=4096, n_features=512, seed=77
    )
    m32 = FederatedLogisticRegression(dataw)
    m16 = FederatedLogisticRegression(dataw, compute_dtype=jnp.bfloat16)
    f32, x1 = None, None
    fl0, unr = ravel_pytree(m32.init_params())
    key = jax.random.PRNGKey(3)
    xw = fl0[None, :] + 0.01 * jax.random.normal(key, (4, fl0.shape[0]))

    def vg(model):
        return jax.jit(
            jax.vmap(
                lambda x: jax.value_and_grad(
                    lambda v: model.logp(unr(v))
                )(x)
            )
        )

    for prec in ("default", "highest"):
        with jax.default_matmul_precision(prec):
            v32, g32 = vg(m32)(xw)
            v16, g16 = vg(m16)(xw)
            jax.block_until_ready(g16)
        v32, v16 = np.asarray(v32, np.float64), np.asarray(v16, np.float64)
        relv = np.max(np.abs(v16 - v32) / np.abs(v32))
        relg = np.max(
            np.abs(np.asarray(g16, np.float64) - np.asarray(g32, np.float64))
        ) / np.max(np.abs(np.asarray(g32)))
        print(
            f"wide-logistic f32-prec={prec}: value relerr {relv:.3e} "
            f"(gate 2e-2), grad relerr {relg:.3e} (gate 5e-2)",
            flush=True,
        )

    # --- 5. exact GP on TPU: is the Cholesky bf16-poisoned? ----------
    # (The chip computes f32 contractions at bf16 accuracy — section
    # 1; a Cholesky built on such dots could corrupt the marginal
    # likelihood.  Compare against the same build on CPU.)
    from pytensor_federated_tpu.models.gp import (
        FederatedExactGP,
        generate_gp_data,
    )

    data_gp, _ = generate_gp_data(8, n_obs=256, seed=9)
    cpu = jax.devices("cpu")[0]
    # 5b acceptance (round-3 verdict item 4): the STRICT policy's
    # on-chip logp must match CPU within 1e-4 relative even if the
    # default policy is bf16-poisoned.
    for pol in ("default", "strict"):
        gp = FederatedExactGP(data_gp, f32_policy=pol)
        p_gp = gp.init_params()
        v_tpu, g_tpu = gp.logp_and_grad(p_gp)
        with jax.default_device(cpu):
            v_cpu, g_cpu = jax.jit(gp.logp_and_grad)(
                jax.device_put(p_gp, cpu)
            )
        rel = abs(float(v_tpu) - float(v_cpu)) / abs(float(v_cpu))
        gflat = np.concatenate(
            [np.ravel(np.asarray(x)) for x in jax.tree_util.tree_leaves(g_tpu)]
        )
        gflat_c = np.concatenate(
            [np.ravel(np.asarray(x)) for x in jax.tree_util.tree_leaves(g_cpu)]
        )
        grel = np.max(np.abs(gflat - gflat_c)) / np.max(np.abs(gflat_c))
        verdict = "PASS" if rel <= 1e-4 else "FAIL"
        print(
            f"exact_gp 8x256 policy={pol}: v_tpu={float(v_tpu):.6g} "
            f"v_cpu={float(v_cpu):.6g} relerr {rel:.3e} "
            f"[{verdict} @1e-4], grad relerr {grel:.3e}",
            flush=True,
        )

    print("diag complete", flush=True)


if __name__ == "__main__":
    main()
