"""Capture a JAX profiler trace of the flagship chain on a live chip.

Produces the "profile, iterate" artifact the sharding/collective
workflow calls for: a perfetto/xplane trace of the warm flagship
logp+grad chain (plus one cold dispatch), written under
``tools/trace/<timestamp>/``.  Run only on a LIVE chip during an idle
window (probe first; never under a timeout):

    python tools/tpu_trace.py [--n 20000]

View with ui.perfetto.dev or xprof.  The trace answers the questions a
rate alone cannot: per-iteration loop overhead vs compute, transfer
stalls, and fusion boundaries of the chained executable.
"""

from __future__ import annotations

import argparse
import datetime
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=20_000,
                    help="chain length to trace (warm executable)")
    ap.add_argument("--probe-timeout-s", type=float, default=150.0)
    args = ap.parse_args()

    sys.path.insert(0, REPO)
    from pytensor_federated_tpu.utils import probe_backend

    live, _ = probe_backend(timeout_s=args.probe_timeout_s)
    if not live:
        print("TPU not live — not tracing.", file=sys.stderr)
        return 1

    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from bench import make_chained
    from pytensor_federated_tpu.models.linear import (
        FederatedLinearRegression,
        generate_node_data,
    )

    data, _ = generate_node_data(8, n_obs=64, seed=123)
    model = FederatedLinearRegression(data)
    flat0, unravel = ravel_pytree(model.init_params())

    def fn(x):
        return jax.value_and_grad(lambda v: model.logp(unravel(v)))(x)

    chained = make_chained(fn)
    # Warm (compile) OUTSIDE the trace so the trace shows steady state.
    jax.block_until_ready(chained(flat0, jnp.asarray(100, jnp.int32)))

    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y%m%dT%H%M%SZ"
    )
    out_dir = os.path.join(REPO, "tools", "trace", stamp)
    os.makedirs(out_dir, exist_ok=True)
    with jax.profiler.trace(out_dir):
        out = chained(flat0, jnp.asarray(args.n, jnp.int32))
        jax.block_until_ready(out)
    print(f"trace written to {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
