"""Liveness-poll the TPU and auto-capture benchmarks on the first live window.

VERDICT r2 item 7: the chip was wedged for two full rounds and a manual
"run it when live" step keeps missing the window.  This script is the
automation: every invocation appends one line to
``tools/capture_attempts.log`` (git-tracked on purpose — it IS the
"log showing attempts" evidence the verdict asks for), and — on the
first live window with an idle machine — runs ``tools/tpu_capture.py``
(which probes liveness itself, refuses a busy machine, and verifies the
artifacts really say ``backend: tpu``).  Compiled Mosaic, the suspected
relay-wedge trigger (CLAUDE.md), only runs AFTER a successful plain
capture, as a bench-only second pass (``--no-mosaic-after`` disables).

Safe by construction (CLAUDE.md wedge policy):

- the probe runs jax in a *subprocess* under a timeout
  (:func:`pytensor_federated_tpu.utils.probe_backend`) so a wedged relay
  can never hang the poller, and
- the capture itself runs with NO timeout — killing a process mid-TPU-call
  is exactly what wedges the chip.

Run once per poll (e.g. from cron/systemd every ~45 min, or a driver
loop)::

    python tools/tpu_poll.py            # probe, log, capture if live
    python tools/tpu_poll.py --dry-run  # probe + log only, never capture
"""

from __future__ import annotations

import argparse
import datetime
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tools", "capture_attempts.log")


def _log(line: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )
    entry = f"{stamp} {line}"
    print(entry)
    with open(LOG, "a", encoding="utf-8") as fh:
        fh.write(entry + "\n")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dry-run", action="store_true")
    ap.add_argument("--timeout-s", type=float, default=150.0)
    ap.add_argument(
        "--loop-every-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="poll forever at this interval in ONE long-lived process "
        "(for environments without cron); exits 0 after the first "
        "successful capture so the operator notices",
    )
    ap.add_argument(
        "--mosaic-after",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="after a SUCCESSFUL plain capture, re-run bench.py once "
        "with compiled Mosaic probed (--try-mosaic --skip-suite) to "
        "settle the Pallas question; never on the first pass — Mosaic "
        "is the suspected wedge trigger, so the safe artifacts land "
        "before the experiment runs",
    )
    args = ap.parse_args(argv)

    sys.path.insert(0, REPO)
    # Explicit tools/ entry: the implicit script-dir path only exists
    # when invoked as `python tools/tpu_poll.py`, not under -m or import
    # (_attempt imports the sibling tpu_capture module through it).
    sys.path.insert(0, os.path.join(REPO, "tools"))

    if args.dry_run:
        from pytensor_federated_tpu.utils import probe_backend

        live, _ = probe_backend(timeout_s=args.timeout_s)
        if not live:
            # A dead/wedged window must leave FORENSICS, not just a log
            # line: the bundle carries the probe verdict's flight-
            # record tail + this process's state (ISSUE 2 satellite).
            _log(
                "probe: DEAD (dry run); incident bundle -> "
                + _probe_incident(args.timeout_s)
            )
            return 1
        _log("probe: LIVE (dry run)")
        return 0

    if args.loop_every_s is not None:
        import time

        while True:
            rc = _attempt(args)
            if rc == 0:
                _log("loop: capture succeeded — exiting so it is noticed")
                return 0
            time.sleep(args.loop_every_s)

    return _attempt(args)


def _probe_incident(timeout_s: float) -> str:
    """Write a watchdog incident bundle for a failed liveness probe;
    returns its path (logged into capture_attempts.log by callers so a
    wedged window leaves an artifact, not just a line).  Bundles land
    in tools/incidents/ — next to the log they are referenced from."""
    from pytensor_federated_tpu.telemetry.watchdog import (
        write_incident_bundle,
    )

    inc_dir = os.path.join(REPO, "tools", "incidents")
    os.makedirs(inc_dir, exist_ok=True)
    path = write_incident_bundle(
        "tpu-liveness-probe-timeout",
        attrs={"probe_timeout_s": timeout_s},
        dir=inc_dir,
    )
    return os.path.relpath(path, REPO)


def _attempt(args) -> int:
    from tpu_capture import EXIT_MEANINGS

    # One probe total: tpu_capture does its own liveness/busy preflight,
    # so the poller just invokes it and logs the outcome (a poll-side
    # probe would dial the tunnel a second time for no information).
    # No timeout on purpose — see module docstring.  Compiled Mosaic is
    # deliberately NOT probed here: CLAUDE.md marks it a suspected relay
    # wedge trigger, so the unattended path secures the plain artifacts
    # first and only then (below) runs the Mosaic experiment.
    capture = os.path.join(REPO, "tools", "tpu_capture.py")
    res = subprocess.run(
        [sys.executable, capture, "--probe-timeout-s", str(args.timeout_s)],
        cwd=REPO,
    )
    why = EXIT_MEANINGS.get(res.returncode, "unknown failure")
    _log(f"capture attempt: exit={res.returncode} ({why})")
    if res.returncode == 1:
        # Exit 1 = the capture's own liveness probe timed out (a
        # wedged tunnel) — leave the incident bundle's path in the
        # attempts log so the window's forensics are findable later.
        _log("incident bundle -> " + _probe_incident(args.timeout_s))
    if res.returncode != 0 or not args.mosaic_after:
        return res.returncode

    # Artifacts are safe on disk — now settle VERDICT item 2 (Pallas
    # compiled-Mosaic: win, lose, or wedge) with the bench-only pass.
    _log("mosaic settle: starting tpu_capture.py --try-mosaic --skip-suite")
    mres = subprocess.run(
        [sys.executable, capture, "--try-mosaic", "--skip-suite",
         "--probe-timeout-s", str(args.timeout_s)],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    out_path = os.path.join(REPO, "tools", "mosaic_settle.out")
    with open(out_path, "w", encoding="utf-8") as fh:
        fh.write(mres.stdout)
        fh.write("\n--- stderr ---\n")
        fh.write(mres.stderr)
    _log(
        f"mosaic settle: exit={mres.returncode} "
        f"({EXIT_MEANINGS.get(mres.returncode, 'unknown failure')}); "
        f"output -> {os.path.relpath(out_path, REPO)}"
    )

    # Harvest the rest of the live window: the numerics diagnostic
    # (f32-precision experiments, GP-vs-CPU check) and a profiler
    # trace of the flagship chain.  Both are advisory — logged, never
    # allowed to fail the poll — and each runs to completion
    # (no timeout: killing mid-TPU-call is the wedge trigger).
    for name, script, out_name in (
        ("diag", "diag_tpu.py", "diag_tpu_live.out"),
        ("trace", "tpu_trace.py", "tpu_trace_live.out"),
    ):
        spath = os.path.join(REPO, "tools", script)
        dres = subprocess.run(
            [sys.executable, spath],
            cwd=REPO,
            capture_output=True,
            text=True,
        )
        dout = os.path.join(REPO, "tools", out_name)
        with open(dout, "w", encoding="utf-8") as fh:
            fh.write(dres.stdout)
            fh.write("\n--- stderr ---\n")
            fh.write(dres.stderr)
        _log(
            f"{name}: exit={dres.returncode}; "
            f"output -> {os.path.relpath(dout, REPO)}"
        )
    return 0  # plain capture succeeded; the rest is advisory


if __name__ == "__main__":
    raise SystemExit(main())
