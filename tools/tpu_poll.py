"""Liveness-poll the TPU and auto-capture benchmarks on the first live window.

VERDICT r2 item 7: the chip was wedged for two full rounds and a manual
"run it when live" step keeps missing the window.  This script is the
automation: every invocation appends one line to
``tools/capture_attempts.log`` recording the probe outcome, and — on the
first live window with an idle machine — runs
``tools/tpu_capture.py --try-mosaic`` (which re-probes, refuses a busy
machine, and verifies the artifacts really say ``backend: tpu``).

Safe by construction (CLAUDE.md wedge policy):

- the probe runs jax in a *subprocess* under a timeout
  (:func:`pytensor_federated_tpu.utils.probe_backend`) so a wedged relay
  can never hang the poller, and
- the capture itself runs with NO timeout — killing a process mid-TPU-call
  is exactly what wedges the chip.

Run once per poll (e.g. from cron/systemd every ~45 min, or a driver
loop)::

    python tools/tpu_poll.py            # probe, log, capture if live
    python tools/tpu_poll.py --dry-run  # probe + log only, never capture
"""

from __future__ import annotations

import argparse
import datetime
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tools", "capture_attempts.log")


def _log(line: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )
    entry = f"{stamp} {line}"
    print(entry)
    with open(LOG, "a", encoding="utf-8") as fh:
        fh.write(entry + "\n")


# tpu_capture.py's exit codes, for legible attempt logs.
_CAPTURE_EXITS = {
    0: "OK — artifacts captured with backend: tpu",
    1: "DEAD (probe timed out)",
    2: "LIVE but machine busy — not capturing",
    3: "bench.py printed no JSON line",
    4: "bench ran on non-tpu backend (re-wedge?)",
    5: "bench_suite.py failed",
    6: "suite backends not all-tpu (re-wedge mid-capture?)",
}


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dry-run", action="store_true")
    ap.add_argument("--timeout-s", type=float, default=150.0)
    args = ap.parse_args(argv)

    if args.dry_run:
        sys.path.insert(0, REPO)
        from pytensor_federated_tpu.utils import probe_backend

        live, _ = probe_backend(timeout_s=args.timeout_s)
        _log(f"probe: {'LIVE' if live else 'DEAD'} (dry run)")
        return 0 if live else 1

    # One probe total: tpu_capture does its own liveness/busy preflight,
    # so the poller just invokes it and logs the outcome (a poll-side
    # probe would dial the tunnel a second time for no information).
    # No timeout on purpose — see module docstring.
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_capture.py"),
         "--try-mosaic", "--probe-timeout-s", str(args.timeout_s)],
        cwd=REPO,
    )
    why = _CAPTURE_EXITS.get(res.returncode, "unknown failure")
    _log(f"capture attempt: exit={res.returncode} ({why})")
    return res.returncode


if __name__ == "__main__":
    raise SystemExit(main())
