"""Generate docs/api.md from the package's public surface.

Run:  python tools/gen_api_docs.py          (writes docs/api.md)
      python tools/gen_api_docs.py --check  (exit 1 if stale)

Walks the top-level package plus each subpackage's ``__all__`` and
emits one line per public name: its kind, signature (for callables),
and the first line of its docstring.  tests/test_api_docs.py keeps the
committed file in sync.
"""

from __future__ import annotations

import inspect
import sys
import typing
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

MODULES = [
    "pytensor_federated_tpu",
    # fed subsystem (ISSUE 6): the MapReduce primitives, the placement
    # surface, and the program/fusion entry points are each documented
    # at module level — a deployment picks a placement, a model author
    # reads the primitives.
    "pytensor_federated_tpu.fed",
    "pytensor_federated_tpu.fed.primitives",
    "pytensor_federated_tpu.fed.placements",
    "pytensor_federated_tpu.fed.lowering",
    "pytensor_federated_tpu.fed.batching",
    "pytensor_federated_tpu.models",
    "pytensor_federated_tpu.ops",
    "pytensor_federated_tpu.parallel",
    "pytensor_federated_tpu.samplers",
    "pytensor_federated_tpu.service",
    # Micro-batching engine (ISSUE 3): the coalescing/batched-wire
    # surface is its own module, documented directly like the incident
    # modules below.
    "pytensor_federated_tpu.service.batching",
    # Wire-constant registry (ISSUE 7): the declared source graftlint's
    # wire-registry rule checks every implementation against.
    "pytensor_federated_tpu.service.wire_registry",
    # Zero-copy shm transport (ISSUE 9): the arena's slot/generation
    # protocol and the doorbell client/server pair are public surface
    # — a colocated deployment reads both.
    "pytensor_federated_tpu.service.arena",
    "pytensor_federated_tpu.service.shm",
    # Deadline budgets (ISSUE 10): the contextvar surface every lane
    # propagates and enforces — a deployment binds deadline_scope and
    # classifies DeadlineExceeded.
    "pytensor_federated_tpu.service.deadline",
    # Replica-pool routing (ISSUE 4): the package __init__ re-exports
    # the whole public surface, and the per-module docs cover the
    # pieces a deployment tunes (breaker knobs, policies).
    "pytensor_federated_tpu.routing",
    "pytensor_federated_tpu.routing.pool",
    "pytensor_federated_tpu.routing.policies",
    "pytensor_federated_tpu.routing.breaker",
    # Retry budgets (ISSUE 10): the token bucket every amplifying
    # recovery path spends from.
    "pytensor_federated_tpu.routing.budget",
    # Gradient sharding on the wire (ISSUE 13).
    "pytensor_federated_tpu.routing.partition",
    "pytensor_federated_tpu.telemetry",
    # Incident subsystem (ISSUE 2): flat functional surfaces, so each
    # module's __all__ is documented directly rather than only the
    # names the package re-exports.
    "pytensor_federated_tpu.telemetry.flightrec",
    "pytensor_federated_tpu.telemetry.watchdog",
    "pytensor_federated_tpu.telemetry.reunion",
    # Fleet observability plane (ISSUE 11): collector/merge surface,
    # critical-path analysis, and the SLO burn-rate engine.
    "pytensor_federated_tpu.telemetry.collector",
    "pytensor_federated_tpu.telemetry.critpath",
    "pytensor_federated_tpu.telemetry.slo",
    # Gateway tier (ISSUE 12): the front door — accept tier, tenant
    # fairness vocabulary, and the autoscaler a deployment tunes.
    "pytensor_federated_tpu.gateway",
    "pytensor_federated_tpu.gateway.server",
    "pytensor_federated_tpu.gateway.fairness",
    "pytensor_federated_tpu.gateway.autoscale",
    # Effect-handler probabilistic front end (ISSUE 15): primitives +
    # handlers, the distribution objects, the plate->fed compiler, the
    # shared ELBO core, and the SVI lanes.
    # Sharded optimizer (ISSUE 16): the ZeRO-over-the-pool surface —
    # owner-side compute factory, driver-side ShardedOptimizer, and
    # the checkpoint store whose version protocol carries exactly-once.
    "pytensor_federated_tpu.optim",
    "pytensor_federated_tpu.optim.sharded",
    "pytensor_federated_tpu.optim.state",
    "pytensor_federated_tpu.ppl",
    "pytensor_federated_tpu.ppl.distributions",
    "pytensor_federated_tpu.ppl.handlers",
    "pytensor_federated_tpu.ppl.compiler",
    "pytensor_federated_tpu.ppl.elbo",
    "pytensor_federated_tpu.ppl.svi",
    "pytensor_federated_tpu.ppl.radon",
    # Fault-injection subsystem (ISSUE 5): the plan vocabulary and the
    # runtime primitives the shims call are both public surface — chaos
    # plans are authored against them (docs/robustness.md).
    "pytensor_federated_tpu.faultinject",
    "pytensor_federated_tpu.faultinject.plan",
    "pytensor_federated_tpu.faultinject.runtime",
    "pytensor_federated_tpu.checkpoint",
    "pytensor_federated_tpu.diagnostics",
    # Static-analysis suite (ISSUE 7): the rule registry and runner are
    # public so tests and tools can run single rules programmatically;
    # the rule catalog itself lives in docs/static-analysis.md.
    "pytensor_federated_tpu.analysis",
    # graftflow engine (ISSUE 8): the shared call graph and the
    # dataflow context propagation the interprocedural rules run on.
    "pytensor_federated_tpu.analysis.graph",
    "pytensor_federated_tpu.analysis.dataflow",
    "pytensor_federated_tpu.fed.lint_fixtures",
    "pytensor_federated_tpu.utils",
]

# The bridge's __all__ depends on whether PyTensor is installed
# (import-gated like the reference's __init__), so its section is
# static text — the generated file must be identical in every
# environment or the freshness test flakes.
BRIDGE_SECTION = [
    "",
    "## `pytensor_federated_tpu.bridge`  (requires PyTensor)",
    "",
    "Import-gated: without PyTensor only `HAS_PYTENSOR` (False) exists;"
    " accessing the ops raises an ImportError naming the extra.",
    "",
    "- **`HAS_PYTENSOR`** (const) — whether the PyTensor ops imported",
    "- **`FederatedArraysToArraysOp`** (class) — arrays->arrays PyTensor"
    " Op with jax_funcify dispatch",
    "- **`FederatedLogpGradOp`** (class) — inputs -> [logp, *grads] Op;"
    " .grad() contract of the reference's LogpGradOp",
    "- **`FederatedLogpOp`** (class) — inputs -> scalar logp Op",
    "- **`federated_potential`** (fn) — attach a federated logp term to"
    " a PyMC model (pm.Potential analog)",
]


def _is_typing_alias(obj) -> bool:
    return (
        typing.get_origin(obj) is not None
        or getattr(obj, "__module__", "") == "typing"
    )


def _first_line(obj) -> str:
    doc = inspect.getdoc(obj) or ""
    line = doc.split("\n", 1)[0].strip()
    return line


def _signature(obj) -> str:
    try:
        sig = str(inspect.signature(obj))
    except (TypeError, ValueError):
        return ""
    return sig if len(sig) <= 70 else sig[:67] + "...)"


def generate() -> str:
    import importlib

    # Never dial a pre-registered tunneled-TPU plugin just to read
    # docstrings (CLAUDE.md: CPU-only work must not touch the chip).
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    out = [
        "# API reference",
        "",
        "Generated by `python tools/gen_api_docs.py` — do not edit by",
        "hand (tests/test_api_docs.py enforces freshness).  One line per",
        "public name (`__all__`); see docstrings for details and",
        "`docs/migrating.md` for the reference-name mapping.",
    ]
    for modname in MODULES:
        mod = importlib.import_module(modname)
        names = getattr(mod, "__all__", None)
        if names is None:
            names = [n for n in dir(mod) if not n.startswith("_")]
            names = [
                n
                for n in names
                if getattr(getattr(mod, n), "__module__", "").startswith(
                    "pytensor_federated_tpu"
                )
            ]
        out += ["", f"## `{modname}`", ""]
        for name in sorted(set(names)):
            obj = getattr(mod, name)
            if _is_typing_alias(obj):
                out.append(f"- **`{name}`** (type alias) = `{obj}`")
                continue
            if inspect.isclass(obj):
                kind = "class"
            elif callable(obj):
                kind = "fn"
            else:
                kind = "const"
            sig = _signature(obj) if kind == "fn" else ""
            if kind == "const" and isinstance(obj, (str, int, float, bool)):
                # A plain value's "docstring" is its type's — show the
                # value itself instead.
                out.append(f"- **`{name}`** (const) = `{obj!r}`")
                continue
            doc = _first_line(obj)
            entry = f"- **`{name}{sig}`** ({kind})"
            if doc:
                entry += f" — {doc}"
            out.append(entry)
    out += BRIDGE_SECTION
    return "\n".join(out) + "\n"


def main() -> int:
    target = REPO / "docs" / "api.md"
    content = generate()
    if "--check" in sys.argv:
        if not target.exists() or target.read_text() != content:
            print("docs/api.md is stale; run python tools/gen_api_docs.py")
            return 1
        print("docs/api.md is up to date")
        return 0
    target.write_text(content)
    print(f"wrote {target} ({len(content.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
