#!/usr/bin/env python
"""One-shot telemetry scraper for live-TPU capture sessions.

A node (or driver) started with a telemetry exposition endpoint —
``serve(..., metrics_port=...)``, or
``telemetry.start_exporter(port=...)`` — serves ``/metrics``
(Prometheus text), ``/snapshot`` and ``/traces`` (JSON).  This tool
pulls ONE sample and either prints it or appends a timestamped JSON
line to a .jsonl file, the same shape ``telemetry.dump_jsonl`` writes
in-process — so a capture session (tools/tpu_poll.py between configs)
can log the RPC/span picture of a live window without importing jax or
touching the PJRT plugin: it is pure stdlib HTTP against loopback.

Usage:
    python tools/metrics_dump.py --port 9100                 # snapshot JSON
    python tools/metrics_dump.py --port 9100 --text          # /metrics text
    python tools/metrics_dump.py --port 9100 --out tools/telemetry.jsonl

Exit status 0 on a successful scrape, 1 on an unreachable/failed
endpoint (so capture scripts can `|| true` it without masking other
errors).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def scrape(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument(
        "--text",
        action="store_true",
        help="print GET /metrics (Prometheus text) instead of the "
        "JSON snapshot",
    )
    ap.add_argument(
        "--out",
        default=None,
        help="append the snapshot as one JSON line to this file "
        "(default: pretty-print to stdout; ignored with --text)",
    )
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    base = f"http://{args.host}:{args.port}"
    try:
        if args.text:
            sys.stdout.write(
                scrape(f"{base}/metrics", args.timeout).decode("utf-8")
            )
            return 0
        body = scrape(f"{base}/snapshot", args.timeout)
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        print(f"metrics_dump: {base} unreachable: {e}", file=sys.stderr)
        return 1

    rec = {"ts": time.time(), "endpoint": base, **json.loads(body)}
    if args.out:
        with open(args.out, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(rec) + "\n")
        print(f"metrics_dump: appended 1 line to {args.out}", file=sys.stderr)
    else:
        json.dump(rec, sys.stdout, indent=2)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
