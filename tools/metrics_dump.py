#!/usr/bin/env python
"""One-shot telemetry scraper for live-TPU capture sessions.

A node (or driver) started with a telemetry exposition endpoint —
``serve(..., metrics_port=...)``, or
``telemetry.start_exporter(port=...)`` — serves ``/metrics``
(Prometheus text), ``/snapshot`` and ``/traces`` (JSON).  This tool
pulls ONE sample and either prints it or appends a timestamped JSON
line to a .jsonl file, the same shape ``telemetry.dump_jsonl`` writes
in-process — so a capture session (tools/tpu_poll.py between configs)
can log the RPC/span picture of a live window without importing jax or
touching the PJRT plugin: it is pure stdlib HTTP against loopback.

Usage:
    python tools/metrics_dump.py --port 9100                 # snapshot JSON
    python tools/metrics_dump.py --port 9100 --snapshot      # same, explicit
    python tools/metrics_dump.py --port 9100 --traces        # /traces JSON
    python tools/metrics_dump.py --port 9100 --text          # /metrics text
    python tools/metrics_dump.py --port 9100 --out tools/telemetry.jsonl
    python tools/metrics_dump.py --port 9100 --grep batch    # batcher families
    python tools/metrics_dump.py --port 9100 --pool          # replica health
    python tools/metrics_dump.py --fleet h:p,h:p,...         # fleet view

``--fleet host:port,host:port,...`` scrapes EVERY listed exposition
endpoint's ``/snapshot`` in one shot and renders the merged fleet
table: one health row per replica (requests/errors served, in-flight
depth, queue-wait and compute p99 from that replica's histograms,
estimated clock offset from the scrape RTT midpoint) plus a ``fleet``
totals row whose quantiles come from the bucket-wise histogram merge
— the same semantics as ``telemetry.collector.merge_metric_snapshots``
(the canonical implementation; the compact one here keeps this tool
importable without jax).  Exit 1 when ANY replica is unreachable —
matching ``--pool`` semantics: a half-scraped fleet is a loud
failure, never a silently partial table.  ``--out`` appends the
per-replica snapshots as one JSON line.

``--pool`` renders the replica-pool picture from the ``pftpu_pool_*``
families (routing/NodePool): one row per replica — breaker-admitted
(up), last advertised queue depth, observed EWMA latency — plus the
breaker-state counts and failover/hedge totals.  Exit 1 when the
endpoint carries no pool families (the process isn't running a pool).

``--grep SUBSTR`` filters to metric families whose name contains
SUBSTR — e.g. ``--grep batch`` prints the micro-batcher picture
(``pftpu_server_batch_size``, ``pftpu_server_batch_wait_seconds``,
``pftpu_server_batches_total``, ``pftpu_client_batch_frame_requests``)
without the rest of the registry.  Works on both the text exposition
and the JSON snapshot's ``metrics`` map.

Exit status 0 on a successful scrape, 1 on an unreachable endpoint OR
a malformed response (wrong JSON shape, non-exposition text) — so
capture scripts can `|| true` it without masking other errors, and a
half-up endpoint cannot masquerade as a good sample.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def scrape(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _children(metrics: dict, family: str):
    return (metrics.get(family) or {}).get("children") or []


def render_pool_view(metrics: dict) -> str:
    """Per-replica health/load table from the ``pftpu_pool_*`` gauges
    in a /snapshot metrics map; '' when no pool families are present."""
    up = {
        c["labels"]["replica"]: c["value"]
        for c in _children(metrics, "pftpu_pool_replica_up")
    }
    if not up:
        return ""
    depth = {
        c["labels"]["replica"]: c["value"]
        for c in _children(metrics, "pftpu_pool_replica_queue_depth")
    }
    ewma = {
        c["labels"]["replica"]: c["value"]
        for c in _children(metrics, "pftpu_pool_replica_ewma_seconds")
    }
    rows = [("replica", "up", "queue_depth", "ewma_ms")]
    for replica in sorted(up):
        d = depth.get(replica)
        e = ewma.get(replica)
        rows.append(
            (
                replica,
                "yes" if up[replica] else "NO",
                "-" if d is None or d < 0 else str(int(d)),
                "-" if not e else f"{1e3 * e:.2f}",
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(4)]
    out = [
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in rows
    ]
    states = {
        c["labels"]["state"]: int(c["value"])
        for c in _children(metrics, "pftpu_pool_replicas")
    }
    if states:
        out.append(
            "breakers: "
            + " / ".join(
                f"{states.get(s, 0)} {s}"
                for s in ("closed", "half_open", "open")
            )
        )
    totals = []
    for fam, label in (
        ("pftpu_pool_failovers_total", "failovers"),
        ("pftpu_pool_hedges_total", "hedges"),
    ):
        n = sum(c["value"] for c in _children(metrics, fam))
        if n:
            totals.append(f"{label}: {int(n)}")
    if totals:
        out.append("  ".join(totals))
    return "\n".join(out) + "\n"


def _hist_stats(metrics: dict, family: str):
    """-> (count, sum, {bound: n}) pooled over the family's children
    (per-bucket counts, the shared fixed ladder)."""
    count, total, buckets = 0, 0.0, {}
    for c in _children(metrics, family):
        count += int(c.get("count", 0))
        total += float(c.get("sum", 0.0))
        for bound, n in (c.get("buckets") or {}).items():
            b = float(bound)
            buckets[b] = buckets.get(b, 0) + int(n)
    return count, total, buckets


def _bucket_quantile(count: int, buckets: dict, q: float) -> float:
    """Upper-bound-of-bucket quantile, same estimate the in-process
    Histogram.approx_quantile makes."""
    if count <= 0:
        return float("nan")
    rank, seen = q * count, 0
    for bound in sorted(buckets):
        seen += buckets[bound]
        if seen >= rank and buckets[bound]:
            return bound
    return float("inf")


def _counter_total(metrics: dict, family: str) -> float:
    return sum(
        float(c.get("value", 0.0)) for c in _children(metrics, family)
    )


def render_fleet_view(
    scrapes: "list[tuple[str, dict | None, str | None, float, float | None]]",
) -> str:
    """The merged fleet table from per-replica /snapshot payloads:
    ``scrapes`` rows are (address, payload-or-None, error, rtt_s,
    clock_offset_s).  Counters sum and histogram quantiles merge
    bucket-wise across replicas for the ``fleet`` row; a dead replica
    renders a loud NO row and contributes nothing."""
    header = (
        "replica", "up", "requests", "errors", "inflight",
        "queue_p99_ms", "compute_p99_ms", "offset_ms", "rtt_ms",
    )
    rows = [header]
    fleet_req = fleet_err = fleet_inf = 0.0
    fleet_q = [0, 0.0, {}]
    fleet_c = [0, 0.0, {}]
    n_up = 0
    for addr, payload, error, rtt_s, offset_s in scrapes:
        if payload is None:
            rows.append(
                (addr, "NO", "-", "-", "-", "-", "-", "-",
                 f"{1e3 * rtt_s:.1f}")
            )
            continue
        n_up += 1
        metrics = payload.get("metrics") or {}
        req = _counter_total(metrics, "pftpu_server_requests_total")
        err = _counter_total(metrics, "pftpu_server_errors_total")
        inf_ = _counter_total(metrics, "pftpu_server_inflight_requests")
        qn, qs, qb = _hist_stats(metrics, "pftpu_server_queue_wait_seconds")
        cn, cs, cb = _hist_stats(metrics, "pftpu_server_compute_seconds")
        fleet_req += req
        fleet_err += err
        fleet_inf += inf_
        for agg, (n, s, b) in ((fleet_q, (qn, qs, qb)),
                               (fleet_c, (cn, cs, cb))):
            agg[0] += n
            agg[1] += s
            for bound, cnt in b.items():
                agg[2][bound] = agg[2].get(bound, 0) + cnt
        q99 = _bucket_quantile(qn, qb, 0.99)
        c99 = _bucket_quantile(cn, cb, 0.99)
        rows.append(
            (
                addr, "yes", str(int(req)), str(int(err)),
                str(int(inf_)),
                "-" if q99 != q99 else f"{1e3 * q99:.2f}",
                "-" if c99 != c99 else f"{1e3 * c99:.2f}",
                "-" if offset_s is None else f"{1e3 * offset_s:+.1f}",
                f"{1e3 * rtt_s:.1f}",
            )
        )
    q99 = _bucket_quantile(fleet_q[0], fleet_q[2], 0.99)
    c99 = _bucket_quantile(fleet_c[0], fleet_c[2], 0.99)
    rows.append(
        (
            f"fleet ({n_up}/{len(scrapes)} up)", "",
            str(int(fleet_req)), str(int(fleet_err)),
            str(int(fleet_inf)),
            "-" if q99 != q99 else f"{1e3 * q99:.2f}",
            "-" if c99 != c99 else f"{1e3 * c99:.2f}",
            "", "",
        )
    )
    widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
    return "\n".join(
        "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        for row in rows
    ) + "\n"


def _filter_exposition(text: str, substr: str) -> str:
    """Keep only the exposition blocks of families whose name contains
    ``substr``.  A block is the ``# HELP``/``# TYPE`` pair plus its
    sample lines; family tracking keys off the HELP header so suffixed
    sample names (_bucket/_sum/_count) follow their family."""
    out = []
    keep = False
    for line in text.splitlines():
        if line.startswith("# HELP "):
            family = line.split(" ", 3)[2]
            keep = substr in family
        if keep:
            out.append(line)
    return "\n".join(out) + "\n" if out else ""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--port", type=int, default=None,
        help="exposition endpoint port (required unless --fleet)",
    )
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument(
        "--fleet",
        default=None,
        metavar="HOST:PORT,...",
        help="scrape every listed /snapshot endpoint and render the "
        "merged fleet table (exit 1 if ANY replica is unreachable)",
    )
    mode.add_argument(
        "--text",
        action="store_true",
        help="print GET /metrics (Prometheus text) instead of the "
        "JSON snapshot",
    )
    mode.add_argument(
        "--snapshot",
        action="store_true",
        help="GET /snapshot (the default mode, made explicit)",
    )
    mode.add_argument(
        "--traces",
        action="store_true",
        help="GET /traces — recent completed span trees only",
    )
    mode.add_argument(
        "--pool",
        action="store_true",
        help="render per-replica pool health/load from the "
        "pftpu_pool_* families of the /snapshot metrics map",
    )
    ap.add_argument(
        "--out",
        default=None,
        help="append the scrape as one JSON line to this file "
        "(default: pretty-print to stdout; ignored with --text)",
    )
    ap.add_argument(
        "--grep",
        default=None,
        metavar="SUBSTR",
        help="only metric families whose name contains SUBSTR "
        "(e.g. 'batch' for the micro-batcher families); applies to "
        "--text and the snapshot's metrics map",
    )
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    if args.fleet is not None:
        scrapes = []
        n_dead = 0
        for spec in args.fleet.split(","):
            spec = spec.strip()
            if not spec:
                continue
            host, _, port = spec.rpartition(":")
            addr = f"{host or args.host}:{port}"
            t0_wall = time.time()
            t0 = time.monotonic()
            try:
                body = scrape(
                    f"http://{addr}/snapshot", args.timeout
                )
                payload = json.loads(body)
                if not isinstance(payload, dict) or not isinstance(
                    payload.get("metrics"), dict
                ):
                    raise ValueError("no 'metrics' map in /snapshot")
            except (
                urllib.error.URLError, OSError, TimeoutError, ValueError,
            ) as e:
                n_dead += 1
                print(
                    f"metrics_dump: {addr} unreachable: {e}",
                    file=sys.stderr,
                )
                scrapes.append(
                    (addr, None, str(e), time.monotonic() - t0, None)
                )
                continue
            rtt = time.monotonic() - t0
            node_ts = payload.get("ts")
            offset = (
                node_ts - (t0_wall + time.time()) / 2.0
                if isinstance(node_ts, (int, float))
                else None
            )
            scrapes.append((addr, payload, None, rtt, offset))
        if not scrapes:
            print("metrics_dump: --fleet lists no endpoints",
                  file=sys.stderr)
            return 2
        sys.stdout.write(render_fleet_view(scrapes))
        if args.out:
            rec = {
                "ts": time.time(),
                "fleet": {
                    addr: payload
                    for addr, payload, _e, _r, _o in scrapes
                    if payload is not None
                },
                "unreachable": [
                    addr
                    for addr, payload, _e, _r, _o in scrapes
                    if payload is None
                ],
            }
            with open(args.out, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(rec) + "\n")
            print(
                f"metrics_dump: appended 1 line to {args.out}",
                file=sys.stderr,
            )
        # --pool semantics: any unreachable replica is a failed scrape.
        return 1 if n_dead else 0

    if args.port is None:
        ap.error("--port is required (or use --fleet)")
    base = f"http://{args.host}:{args.port}"
    route = "/traces" if args.traces else "/snapshot"
    try:
        if args.text:
            text = scrape(f"{base}/metrics", args.timeout).decode(
                "utf-8", "replace"
            )
            # An endpoint that answers but serves something other than
            # exposition text (a proxy error page, a different service
            # on the port) must not count as a good scrape.  An EMPTY
            # registry legitimately renders "", anything else starts
            # with a HELP header.
            if text and not text.startswith("# HELP "):
                print(
                    f"metrics_dump: {base}/metrics returned non-"
                    "exposition text",
                    file=sys.stderr,
                )
                return 1
            if args.grep:
                text = _filter_exposition(text, args.grep)
            sys.stdout.write(text)
            return 0
        body = scrape(base + route, args.timeout)
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        print(f"metrics_dump: {base} unreachable: {e}", file=sys.stderr)
        return 1

    try:
        payload = json.loads(body)
    except ValueError as e:
        print(
            f"metrics_dump: {base}{route} returned malformed JSON: {e}",
            file=sys.stderr,
        )
        return 1
    # Shape check per route: /snapshot is a dict with a metrics map,
    # /traces a list of span trees.  A well-formed-but-wrong payload is
    # the same operational failure as garbage.
    if args.pool:
        if not isinstance(payload, dict) or not isinstance(
            payload.get("metrics"), dict
        ):
            print(
                f"metrics_dump: {base}/snapshot has no 'metrics' map",
                file=sys.stderr,
            )
            return 1
        view = render_pool_view(payload["metrics"])
        if not view:
            print(
                f"metrics_dump: {base} exposes no pftpu_pool_* "
                "families (no replica pool in that process)",
                file=sys.stderr,
            )
            return 1
        sys.stdout.write(view)
        return 0
    if args.traces:
        if not isinstance(payload, list):
            print(
                f"metrics_dump: {base}/traces is not a JSON list",
                file=sys.stderr,
            )
            return 1
        rec = {"ts": time.time(), "endpoint": base, "traces": payload}
    else:
        if not isinstance(payload, dict) or "metrics" not in payload:
            print(
                f"metrics_dump: {base}/snapshot has no 'metrics' key",
                file=sys.stderr,
            )
            return 1
        if args.grep and isinstance(payload["metrics"], dict):
            payload = {
                **payload,
                "metrics": {
                    k: v
                    for k, v in payload["metrics"].items()
                    if args.grep in k
                },
            }
        rec = {"ts": time.time(), "endpoint": base, **payload}

    if args.out:
        with open(args.out, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(rec) + "\n")
        print(f"metrics_dump: appended 1 line to {args.out}", file=sys.stderr)
    else:
        json.dump(rec, sys.stdout, indent=2)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
