#!/usr/bin/env python
"""One-shot telemetry scraper for live-TPU capture sessions.

A node (or driver) started with a telemetry exposition endpoint —
``serve(..., metrics_port=...)``, or
``telemetry.start_exporter(port=...)`` — serves ``/metrics``
(Prometheus text), ``/snapshot`` and ``/traces`` (JSON).  This tool
pulls ONE sample and either prints it or appends a timestamped JSON
line to a .jsonl file, the same shape ``telemetry.dump_jsonl`` writes
in-process — so a capture session (tools/tpu_poll.py between configs)
can log the RPC/span picture of a live window without importing jax or
touching the PJRT plugin: it is pure stdlib HTTP against loopback.

Usage:
    python tools/metrics_dump.py --port 9100                 # snapshot JSON
    python tools/metrics_dump.py --port 9100 --snapshot      # same, explicit
    python tools/metrics_dump.py --port 9100 --traces        # /traces JSON
    python tools/metrics_dump.py --port 9100 --text          # /metrics text
    python tools/metrics_dump.py --port 9100 --out tools/telemetry.jsonl
    python tools/metrics_dump.py --port 9100 --grep batch    # batcher families
    python tools/metrics_dump.py --port 9100 --pool          # replica health

``--pool`` renders the replica-pool picture from the ``pftpu_pool_*``
families (routing/NodePool): one row per replica — breaker-admitted
(up), last advertised queue depth, observed EWMA latency — plus the
breaker-state counts and failover/hedge totals.  Exit 1 when the
endpoint carries no pool families (the process isn't running a pool).

``--grep SUBSTR`` filters to metric families whose name contains
SUBSTR — e.g. ``--grep batch`` prints the micro-batcher picture
(``pftpu_server_batch_size``, ``pftpu_server_batch_wait_seconds``,
``pftpu_server_batches_total``, ``pftpu_client_batch_frame_requests``)
without the rest of the registry.  Works on both the text exposition
and the JSON snapshot's ``metrics`` map.

Exit status 0 on a successful scrape, 1 on an unreachable endpoint OR
a malformed response (wrong JSON shape, non-exposition text) — so
capture scripts can `|| true` it without masking other errors, and a
half-up endpoint cannot masquerade as a good sample.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def scrape(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _children(metrics: dict, family: str):
    return (metrics.get(family) or {}).get("children") or []


def render_pool_view(metrics: dict) -> str:
    """Per-replica health/load table from the ``pftpu_pool_*`` gauges
    in a /snapshot metrics map; '' when no pool families are present."""
    up = {
        c["labels"]["replica"]: c["value"]
        for c in _children(metrics, "pftpu_pool_replica_up")
    }
    if not up:
        return ""
    depth = {
        c["labels"]["replica"]: c["value"]
        for c in _children(metrics, "pftpu_pool_replica_queue_depth")
    }
    ewma = {
        c["labels"]["replica"]: c["value"]
        for c in _children(metrics, "pftpu_pool_replica_ewma_seconds")
    }
    rows = [("replica", "up", "queue_depth", "ewma_ms")]
    for replica in sorted(up):
        d = depth.get(replica)
        e = ewma.get(replica)
        rows.append(
            (
                replica,
                "yes" if up[replica] else "NO",
                "-" if d is None or d < 0 else str(int(d)),
                "-" if not e else f"{1e3 * e:.2f}",
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(4)]
    out = [
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in rows
    ]
    states = {
        c["labels"]["state"]: int(c["value"])
        for c in _children(metrics, "pftpu_pool_replicas")
    }
    if states:
        out.append(
            "breakers: "
            + " / ".join(
                f"{states.get(s, 0)} {s}"
                for s in ("closed", "half_open", "open")
            )
        )
    totals = []
    for fam, label in (
        ("pftpu_pool_failovers_total", "failovers"),
        ("pftpu_pool_hedges_total", "hedges"),
    ):
        n = sum(c["value"] for c in _children(metrics, fam))
        if n:
            totals.append(f"{label}: {int(n)}")
    if totals:
        out.append("  ".join(totals))
    return "\n".join(out) + "\n"


def _filter_exposition(text: str, substr: str) -> str:
    """Keep only the exposition blocks of families whose name contains
    ``substr``.  A block is the ``# HELP``/``# TYPE`` pair plus its
    sample lines; family tracking keys off the HELP header so suffixed
    sample names (_bucket/_sum/_count) follow their family."""
    out = []
    keep = False
    for line in text.splitlines():
        if line.startswith("# HELP "):
            family = line.split(" ", 3)[2]
            keep = substr in family
        if keep:
            out.append(line)
    return "\n".join(out) + "\n" if out else ""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument(
        "--text",
        action="store_true",
        help="print GET /metrics (Prometheus text) instead of the "
        "JSON snapshot",
    )
    mode.add_argument(
        "--snapshot",
        action="store_true",
        help="GET /snapshot (the default mode, made explicit)",
    )
    mode.add_argument(
        "--traces",
        action="store_true",
        help="GET /traces — recent completed span trees only",
    )
    mode.add_argument(
        "--pool",
        action="store_true",
        help="render per-replica pool health/load from the "
        "pftpu_pool_* families of the /snapshot metrics map",
    )
    ap.add_argument(
        "--out",
        default=None,
        help="append the scrape as one JSON line to this file "
        "(default: pretty-print to stdout; ignored with --text)",
    )
    ap.add_argument(
        "--grep",
        default=None,
        metavar="SUBSTR",
        help="only metric families whose name contains SUBSTR "
        "(e.g. 'batch' for the micro-batcher families); applies to "
        "--text and the snapshot's metrics map",
    )
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    base = f"http://{args.host}:{args.port}"
    route = "/traces" if args.traces else "/snapshot"
    try:
        if args.text:
            text = scrape(f"{base}/metrics", args.timeout).decode(
                "utf-8", "replace"
            )
            # An endpoint that answers but serves something other than
            # exposition text (a proxy error page, a different service
            # on the port) must not count as a good scrape.  An EMPTY
            # registry legitimately renders "", anything else starts
            # with a HELP header.
            if text and not text.startswith("# HELP "):
                print(
                    f"metrics_dump: {base}/metrics returned non-"
                    "exposition text",
                    file=sys.stderr,
                )
                return 1
            if args.grep:
                text = _filter_exposition(text, args.grep)
            sys.stdout.write(text)
            return 0
        body = scrape(base + route, args.timeout)
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        print(f"metrics_dump: {base} unreachable: {e}", file=sys.stderr)
        return 1

    try:
        payload = json.loads(body)
    except ValueError as e:
        print(
            f"metrics_dump: {base}{route} returned malformed JSON: {e}",
            file=sys.stderr,
        )
        return 1
    # Shape check per route: /snapshot is a dict with a metrics map,
    # /traces a list of span trees.  A well-formed-but-wrong payload is
    # the same operational failure as garbage.
    if args.pool:
        if not isinstance(payload, dict) or not isinstance(
            payload.get("metrics"), dict
        ):
            print(
                f"metrics_dump: {base}/snapshot has no 'metrics' map",
                file=sys.stderr,
            )
            return 1
        view = render_pool_view(payload["metrics"])
        if not view:
            print(
                f"metrics_dump: {base} exposes no pftpu_pool_* "
                "families (no replica pool in that process)",
                file=sys.stderr,
            )
            return 1
        sys.stdout.write(view)
        return 0
    if args.traces:
        if not isinstance(payload, list):
            print(
                f"metrics_dump: {base}/traces is not a JSON list",
                file=sys.stderr,
            )
            return 1
        rec = {"ts": time.time(), "endpoint": base, "traces": payload}
    else:
        if not isinstance(payload, dict) or "metrics" not in payload:
            print(
                f"metrics_dump: {base}/snapshot has no 'metrics' key",
                file=sys.stderr,
            )
            return 1
        if args.grep and isinstance(payload["metrics"], dict):
            payload = {
                **payload,
                "metrics": {
                    k: v
                    for k, v in payload["metrics"].items()
                    if args.grep in k
                },
            }
        rec = {"ts": time.time(), "endpoint": base, **payload}

    if args.out:
        with open(args.out, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(rec) + "\n")
        print(f"metrics_dump: appended 1 line to {args.out}", file=sys.stderr)
    else:
        json.dump(rec, sys.stdout, indent=2)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
